"""Built-in protocol flows.

Capability parity with the reference's core flow library
(core/src/main/kotlin/net/corda/core/flows/ + core/.../internal/):

- ``SendTransactionFlow`` / ``ReceiveTransactionFlow`` — transaction
  propagation with back-chain data vending
  (SendTransactionFlow.kt, ReceiveTransactionFlow.kt:32).
- ``ResolveTransactionsFlow`` — BFS dependency download with a DoS cap,
  then wavefront-parallel verification of the fetched DAG — the TPU-native
  replacement for the reference's sequential depth-first verify loop
  (ResolveTransactionsFlow.kt:38-107; SURVEY.md §2.9 P7).
- ``NotaryFlowClient`` / ``NotaryServiceFlow`` — notarisation round-trip
  (NotaryFlow.kt:35-144), validating and non-validating (tear-off) modes.
- ``FinalityFlow`` — verify → notarise → record → broadcast
  (FinalityFlow.kt:28-62) with ``BroadcastTransactionFlow`` recipients.
- ``CollectSignaturesFlow`` / ``SignTransactionFlow`` — multi-party signing
  (CollectSignaturesFlow.kt).
- ``NotaryChangeFlow`` / ``ContractUpgradeFlow`` — state-replacement
  protocols (NotaryChangeFlow.kt, ContractUpgradeFlow.kt,
  AbstractStateReplacementFlow.kt) over the special ledger tx forms.

Wire shape: after the initial SignedTransaction message the *sender* turns
into a data vendor answering ``FetchRequest`` batches ("tx" /
"attachment" / "end") — the session-local equivalent of the reference's
FetchDataFlow request/response rounds (FetchDataFlow.kt:39-141), except
requests are batched per BFS level rather than one hash per round-trip
(one of the latency wins of the re-design).
"""

from __future__ import annotations

import dataclasses

from corda_tpu.crypto import is_fulfilled_by
from corda_tpu.ledger import (
    ComponentGroupType,
    FilteredTransaction,
    NotaryChangeCommand,
    Party,
    SignedTransaction,
    StateAndRef,
    TransactionBuilder,
    UpgradeCommand,
)
from corda_tpu.serialization import cbe_serializable

from .api import FlowException, FlowLogic, FlowSession, InitiatedBy

# DoS bound on dependency resolution, mirroring the reference's hard cap
# (ResolveTransactionsFlow.kt:76).
MAX_RESOLVE_TRANSACTIONS = 5000


class NotaryException(FlowException):
    """Notarisation failed — double spend, bad time window, wrong notary
    (reference: NotaryException wrapping NotaryError)."""


@cbe_serializable(name="flows.FetchRequest")
@dataclasses.dataclass(frozen=True)
class FetchRequest:
    """One data-vending round: ask the sender for transactions or
    attachments by hash; kind == "end" closes the vending loop."""

    kind: str            # "tx" | "attachment" | "end"
    hashes: tuple = ()


def _verify_sigs(flow: FlowLogic, stx: SignedTransaction, allowed: set) -> None:
    """Flow-side signature verification: routed through the serving
    scheduler (INTERACTIVE class) when the node's ServiceHub runs the
    device-batched verifier tier — concurrent flows' singleton verifies
    then coalesce into one device batch — and the plain host check
    otherwise (identical verdicts either way)."""
    services = getattr(flow, "services", None)
    if services is not None and hasattr(services, "verify_stx_signatures"):
        services.verify_stx_signatures(stx, allowed)
    else:
        stx.verify_signatures_except(allowed)


# --------------------------------------------------------------- vending

def vend_data(flow: FlowLogic, session: FlowSession,
              root_stx: SignedTransaction,
              max_served: int = MAX_RESOLVE_TRANSACTIONS) -> None:
    """Serve the counterparty's FetchRequests from local storage until it
    sends kind="end". Sender side of the back-chain protocol.

    Only hashes in the *back-chain closure* of ``root_stx`` are served: the
    authorised set starts at the root's direct dependencies/attachments and
    grows only when a transaction in the closure is actually served (its
    own dependencies become requestable). A counterparty probing for
    unrelated private transactions gets a rejection, mirroring the
    reference DataVendingFlow's authorised-transaction tracking."""
    services = flow.services
    authorised_tx = {ref.txhash for ref in root_stx.inputs}
    authorised_att = set(root_stx.tx.attachments)
    served = 0
    while True:
        req = session.receive(FetchRequest).unwrap(lambda r: r)
        if req.kind == "end":
            return
        served += len(req.hashes)
        if served > max_served:
            raise FlowException("counterparty requested too much data")
        if req.kind == "tx":
            items = []
            for h in req.hashes:
                if h not in authorised_tx:
                    raise FlowException(
                        f"transaction {h} is not in the back-chain being sent"
                    )
                stx = services.validated_transactions.get(h)
                if stx is None:
                    raise FlowException(f"transaction {h} not found")
                items.append(stx)
                authorised_tx.update(ref.txhash for ref in stx.inputs)
                authorised_att.update(stx.tx.attachments)
            session.send(items)
        elif req.kind == "attachment":
            items = []
            for h in req.hashes:
                if h not in authorised_att:
                    raise FlowException(
                        f"attachment {h} is not referenced by the chain being sent"
                    )
                att = services.attachments.open_attachment(h)
                if att is None:
                    raise FlowException(f"attachment {h} not found")
                items.append(att.data)
            session.send(items)
        else:
            raise FlowException(f"unknown fetch kind {req.kind!r}")


class SendTransactionFlow(FlowLogic):
    """Send ``stx`` and then vend its back-chain / attachments on request
    (reference: SendTransactionFlow + DataVendingFlow)."""

    def __init__(self, session: FlowSession, stx: SignedTransaction):
        self.session = session
        self.stx = stx

    def call(self):
        self.session.send(self.stx)
        vend_data(self, self.session, self.stx)


class ResolveTransactionsFlow(FlowLogic):
    """Fetch and verify every unvalidated dependency of ``stx`` via the
    open session, then record them in topological order.

    The reference walks the back-chain with one request per hash and
    verifies sequentially deps-first (ResolveTransactionsFlow.kt:84-107).
    Here each BFS level is fetched as one batch, and the downloaded DAG is
    verified wavefront-parallel (all transactions of equal depth are one
    batched signature dispatch — parallel/wavefront.py)."""

    def __init__(self, stx: SignedTransaction, session: FlowSession,
                 use_device: bool = False):
        self.stx = stx
        self.session = session
        self.use_device = use_device

    def call(self):
        services = self.services
        storage = services.validated_transactions
        fetched: dict = {}

        # every "is it already in storage?" decision is RECORDED: it gates
        # which fetch ops run, and storage mutates between a park and its
        # replay (this very flow records what it fetches — an unrecorded
        # gate would make the replay skip ops and misalign the op log)
        frontier = self.record(lambda: sorted(
            {ref.txhash for ref in self.stx.inputs
             if ref.txhash not in storage},
            key=lambda h: h.bytes,
        ))
        while frontier:
            if len(fetched) + len(frontier) > MAX_RESOLVE_TRANSACTIONS:
                raise FlowException(
                    f"back-chain exceeds {MAX_RESOLVE_TRANSACTIONS} transactions"
                )
            items = self.session.send_and_receive(
                list, FetchRequest("tx", tuple(frontier))
            ).unwrap(lambda xs: xs)
            if len(items) != len(frontier):
                raise FlowException("wrong number of transactions returned")
            for want, got in zip(frontier, items):
                if not isinstance(got, SignedTransaction) or got.id != want:
                    # downloaded-data integrity: the check of
                    # FetchDataFlow.kt:84-91 — id is the Merkle root of the
                    # received bytes, so a lying peer cannot substitute
                    raise FlowException(f"peer sent wrong transaction for {want}")
                fetched[got.id] = got

            def next_frontier(items=items):
                out = set()
                for got in items:
                    for ref in got.inputs:
                        h = ref.txhash
                        if h not in fetched and h not in storage:
                            out.add(h)
                return sorted(out, key=lambda h: h.bytes)

            frontier = self.record(next_frontier)

        self._fetch_attachments(fetched)
        self.session.send(FetchRequest("end"))

        if fetched:
            def resolve_external(ref):
                stx = storage.get(ref.txhash)
                if stx is None:
                    return None
                return stx.tx.outputs[ref.index]

            result = self.record(lambda: self._verify_and_note(
                fetched, resolve_external
            ))
            order = result["order"]
            services.record_transactions(
                *[fetched[tid] for tid in order]
            )
        return sorted(fetched, key=lambda h: h.bytes)

    def _verify_and_note(self, fetched, resolve_external):
        from corda_tpu.parallel import verify_transaction_dag

        result = verify_transaction_dag(
            fetched,
            resolve_external=resolve_external,
            use_device=self.use_device,
        )
        return {"order": result.order}

    def _fetch_attachments(self, fetched: dict) -> None:
        services = self.services

        def compute_needed():
            needed = set()
            for stx in list(fetched.values()) + [self.stx]:
                for h in stx.tx.attachments:
                    if not services.attachments.has_attachment(h):
                        needed.add(h)
            # contract-code pseudo-attachments are registry hashes, not
            # stored blobs — never fetch those (covers input-contract
            # hashes that TransactionBuilder auto-attached, which outputs
            # alone would miss)
            from corda_tpu.ledger.states import (
                registered_contract_code_hashes,
            )

            needed -= registered_contract_code_hashes()
            return sorted(needed, key=lambda h: h.bytes)

        # recorded for the same reason as the tx frontier: the attachment
        # store mutates between a park and its replay, and this gate
        # decides whether the fetch ops below run at all
        hashes = self.record(compute_needed)
        if not hashes:
            return
        blobs = self.session.send_and_receive(
            list, FetchRequest("attachment", tuple(hashes))
        ).unwrap(lambda xs: xs)
        if len(blobs) != len(hashes):
            raise FlowException("wrong number of attachments returned")
        for want, blob in zip(hashes, blobs):
            got = self.record(
                lambda blob=blob: services.attachments.import_or_get(blob)
            )
            if got != want:
                raise FlowException(f"peer sent wrong attachment for {want}")


class ReceiveTransactionFlow(FlowLogic):
    """Receive a SignedTransaction, resolve + verify its back-chain, verify
    it, optionally record it (reference: ReceiveTransactionFlow.kt:32)."""

    def __init__(self, session: FlowSession,
                 check_sufficient_signatures: bool = True,
                 allowed_missing_keys: set | None = None,
                 check_signatures: bool = True,
                 check_contracts: bool = True,
                 record: bool = False):
        self.session = session
        self.check_sufficient_signatures = check_sufficient_signatures
        self.allowed_missing_keys = allowed_missing_keys or set()
        # check_signatures/check_contracts=False skip verification of the
        # *top-level* transaction only (the back-chain always verifies in
        # ResolveTransactionsFlow) — for callers that re-verify anyway,
        # e.g. the notary service, to keep the hot path single-pass
        self.check_signatures = check_signatures
        self.check_contracts = check_contracts
        self.record_it = record

    def call(self) -> SignedTransaction:
        stx = self.session.receive(SignedTransaction).unwrap(lambda s: s)
        self.sub_flow(ResolveTransactionsFlow(stx, self.session))
        if self.check_signatures:
            allowed = set(self.allowed_missing_keys)
            if not self.check_sufficient_signatures:
                # still demand every *present* signature verifies;
                # completeness is relaxed by the caller's allowed set + notary
                if stx.notary is not None:
                    allowed.add(stx.notary.owning_key)
            _verify_sigs(self, stx, allowed)
        if self.check_contracts:
            ltx = self.services.resolve_to_ledger_transaction(stx)
            ltx.verify()
        if self.record_it:
            self.services.record_transactions(stx)
        return stx


# --------------------------------------------------------------- notary

class NotaryFlowClient(FlowLogic):
    """Request notarisation of ``stx`` from its notary; returns the notary
    signature(s) (reference: NotaryFlow.Client, NotaryFlow.kt:35-92)."""

    def __init__(self, stx: SignedTransaction):
        self.stx = stx

    def flow_fields(self):
        return {"stx": self.stx}

    @classmethod
    def from_flow_fields(cls, fields):
        return cls(fields["stx"])

    def call(self) -> list:
        from corda_tpu.observability.flowprof import flowprof_hint

        stx = self.stx
        notary = stx.notary
        if notary is None:
            raise NotaryException("transaction names no notary")
        _verify_sigs(self, stx, {notary.owning_key})
        # flowprof park hint: every wait this request/response exchange
        # parks or blocks on books to notary_rtt — the notarisation
        # round-trip is the one counterparty wait with a name
        # point of no return: once the request may have reached the
        # notary, a deadline shed would abandon a possibly-committed
        # spend before the vault records it — the inputs would be
        # re-selected and double-spend forever. The deadline still sheds
        # at the notary's own admission door (front-door + batch-window
        # shed); this flow now runs to completion.
        self.commit_pin()
        with flowprof_hint("notary_rtt"):
            session = self.initiate_flow(notary)
            validating = self.services.network_map_cache.is_validating_notary(
                notary
            )
            if validating:
                self.sub_flow(SendTransactionFlow(session, stx))
                sigs = session.receive(list).unwrap(lambda s: s)
            else:
                groups = {
                    ComponentGroupType.INPUTS,
                    ComponentGroupType.TIMEWINDOW,
                    ComponentGroupType.NOTARY,
                }
                ftx = FilteredTransaction.build(
                    stx.tx, lambda comp, group: group in groups
                )
                sigs = session.send_and_receive(list, ftx).unwrap(
                    lambda s: s
                )
        self._validate_response(sigs, notary, stx.id)
        return sigs

    @staticmethod
    def _validate_response(sigs: list, notary: Party, tx_id) -> None:
        if not sigs:
            raise NotaryException("notary returned no signatures")
        for sig in sigs:
            sig.verify(tx_id)
        if not is_fulfilled_by(notary.owning_key, {s.by for s in sigs}):
            raise NotaryException(
                "notary response signatures do not fulfil the notary key"
            )


@InitiatedBy(NotaryFlowClient)
class NotaryServiceFlow(FlowLogic):
    """Responder run by the notary node (reference: NotaryFlow.Service,
    NotaryFlow.kt:114-150). Dispatches on the node's NotaryService type:
    validating services receive the full transaction + back-chain;
    non-validating ones a tear-off."""

    def __init__(self, session: FlowSession):
        self.session = session

    def call(self):
        from corda_tpu.notary import NotaryError
        from corda_tpu.notary.service import (
            BatchedNotaryService,
            SimpleNotaryService,
            ValidatingNotaryService,
        )

        service = self.services.notary_service
        if service is None:
            raise FlowException("this node does not run a notary service")
        caller = str(self.session.counterparty.name)
        try:
            if isinstance(service, SimpleNotaryService):
                ftx = self.session.receive(FilteredTransaction).unwrap(
                    lambda f: f
                )
                self.commit_pin()  # process() commits synchronously
                sig = self.record(lambda: service.process(ftx, caller))
            elif isinstance(service, BatchedNotaryService):
                # the service re-verifies signatures+contracts itself, so
                # receive skips top-level verification (single-pass hot path)
                stx = self.sub_flow(ReceiveTransactionFlow(
                    self.session, check_signatures=False,
                    check_contracts=False,
                ))
                # the propagated deadline sheds at the service's front
                # door (before the request joins a batch); once enqueued
                # the batch may commit, so this responder is past its
                # point of no return — it must wait the request out and
                # deliver the verdict rather than abandon a committed
                # spend (docs/OVERLOAD.md)
                self.commit_pin()
                sig = self.record(lambda: service.request(
                    stx, self.services.load_state, caller
                ).result(timeout=60.0))
            elif isinstance(service, ValidatingNotaryService):
                stx = self.sub_flow(ReceiveTransactionFlow(
                    self.session, check_signatures=False,
                    check_contracts=False,
                ))
                self.commit_pin()  # process() commits synchronously
                sig = self.record(lambda: service.process(
                    stx, self.services.load_state, caller
                ))
            else:
                raise FlowException(
                    f"unsupported notary service {type(service).__name__}"
                )
        except NotaryError as e:
            raise NotaryException(str(e)) from e
        self.session.send([sig])


# --------------------------------------------------------------- finality

class BroadcastTransactionFlow(FlowLogic):
    """Push a finalised transaction to one recipient (reference:
    BroadcastTransactionFlow.kt); the recipient resolves, verifies and
    records it."""

    def __init__(self, recipient: Party, stx: SignedTransaction):
        self.recipient = recipient
        self.stx = stx

    def flow_fields(self):
        return {"recipient": self.recipient, "stx": self.stx}

    @classmethod
    def from_flow_fields(cls, fields):
        return cls(fields["recipient"], fields["stx"])

    def call(self):
        session = self.initiate_flow(self.recipient)
        self.sub_flow(SendTransactionFlow(session, self.stx))
        # wait for the recipient's recorded-ack: when FinalityFlow returns,
        # every broadcast recipient has durably recorded the transaction
        # (stronger than the reference's fire-and-forget broadcast — the
        # deterministic-replay engine makes the ack free)
        ok = session.receive(bool).unwrap(lambda b: b)
        if not ok:
            raise FlowException("recipient failed to record the transaction")


@InitiatedBy(BroadcastTransactionFlow)
class ReceiveBroadcastFlow(FlowLogic):
    def __init__(self, session: FlowSession):
        self.session = session

    def call(self):
        stx = self.sub_flow(ReceiveTransactionFlow(
            self.session, check_sufficient_signatures=True, record=True
        ))
        self.session.send(True)
        return stx


class FinalityFlow(FlowLogic):
    """Verify → notarise → record → broadcast (reference:
    FinalityFlow.kt:28-62)."""

    def __init__(self, stx: SignedTransaction, extra_recipients=()):
        self.stx = stx
        self.extra_recipients = tuple(extra_recipients)

    def flow_fields(self):
        return {"stx": self.stx, "extra_recipients": list(self.extra_recipients)}

    @classmethod
    def from_flow_fields(cls, fields):
        return cls(fields["stx"], tuple(fields["extra_recipients"]))

    def call(self) -> SignedTransaction:
        stx = self.stx
        notary = stx.notary
        allowed = {notary.owning_key} if notary is not None else set()
        _verify_sigs(self, stx, allowed)
        ltx = self.services.resolve_to_ledger_transaction(stx)
        ltx.verify()

        notarised = stx
        if self._needs_notarisation(stx):
            sigs = self.sub_flow(NotaryFlowClient(stx))
            notarised = notarised.plus(sigs)
        self.record(lambda: self.services.record_transactions(notarised) or 0)

        for party in self._recipients(notarised):
            self.sub_flow(BroadcastTransactionFlow(party, notarised))
        return notarised

    @staticmethod
    def _needs_notarisation(stx: SignedTransaction) -> bool:
        # issue-only transactions with no time window carry no notary
        # obligation (reference: needsNotarySignature in FinalityFlow.kt)
        return stx.notary is not None and (
            bool(stx.inputs) or stx.tx.time_window is not None
        )

    def _recipients(self, stx: SignedTransaction) -> list[Party]:
        my_key = self.our_identity.owning_key if self.our_identity else None
        seen: set = set()
        out: list[Party] = []
        participants = []
        for ts in stx.tx.outputs:
            participants.extend(ts.data.participants)
        participants.extend(self.extra_recipients)
        for p in participants:
            party = p
            if not isinstance(p, Party):
                party = self.services.identity_service.well_known_party_from_anonymous(p)
                if party is None:
                    continue  # unknown anonymous participant: not broadcastable
            if my_key is not None and party.owning_key == my_key:
                continue
            if party.owning_key in seen:
                continue
            seen.add(party.owning_key)
            out.append(party)
        return out


# --------------------------------------------------------- multi-signing

class CollectSignaturesFlow(FlowLogic):
    """Gather counterparty signatures over a partially-signed transaction
    (reference: CollectSignaturesFlow.kt). One SendTransactionFlow + reply
    per session; signatures are checked as they arrive."""

    def __init__(self, partially_signed: SignedTransaction, sessions):
        self.partially_signed = partially_signed
        self.sessions = list(sessions)

    def call(self) -> SignedTransaction:
        stx = self.partially_signed
        notary_key = stx.notary.owning_key if stx.notary else None
        required = stx.required_signing_keys
        for session in self.sessions:
            self.sub_flow(SendTransactionFlow(session, stx))
            sigs = session.receive(list).unwrap(lambda s: s)
            for sig in sigs:
                sig.verify(stx.id)
                if sig.by not in required and sig.by != notary_key:
                    raise FlowException(
                        "counterparty signed with a key the transaction "
                        "does not require"
                    )
            stx = stx.plus(sigs)
        allowed = {notary_key} if notary_key is not None else set()
        _verify_sigs(self, stx, allowed)
        return stx


class SignTransactionFlow(FlowLogic):
    """Abstract responder for CollectSignaturesFlow (reference:
    SignTransactionFlow in CollectSignaturesFlow.kt). Subclass and override
    ``check_transaction`` with app-level acceptance rules; raise
    FlowException to reject."""

    def __init__(self, session: FlowSession):
        self.session = session

    def check_transaction(self, stx: SignedTransaction) -> None:
        """App hook — validate business terms before signing."""

    def call(self) -> SignedTransaction:
        my_keys = self.services.key_management_service.keys
        stx = self.sub_flow(ReceiveTransactionFlow(
            self.session, check_sufficient_signatures=False,
            allowed_missing_keys=set(my_keys),
        ))
        self.check_transaction(stx)
        to_sign = stx.required_signing_keys & set(my_keys)
        if not to_sign:
            raise FlowException(
                "transaction does not require a signature from this node"
            )
        sigs = [
            self.record(lambda k=k: self.services.key_management_service.sign(
                stx.id, k
            ))
            for k in sorted(to_sign, key=lambda k: (k.scheme_id, k.encoded))
        ]
        self.session.send(sigs)
        return stx.plus(sigs)


# ----------------------------------------------- state replacement flows

class AbstractStateReplacementFlow:
    """Propose replacing a state with a modified copy, collect every
    participant's approval+signature, then finalise (reference:
    AbstractStateReplacementFlow.kt). Concrete forms: NotaryChangeFlow,
    ContractUpgradeFlow."""

    class Instigator(FlowLogic):
        def __init__(self, state_and_ref: StateAndRef):
            self.state_and_ref = state_and_ref

        def flow_fields(self):
            return {"state_and_ref": self.state_and_ref}

        @classmethod
        def from_flow_fields(cls, fields):
            return cls(fields["state_and_ref"])

        def assemble_builder(self) -> TransactionBuilder:
            raise NotImplementedError

        def call(self) -> StateAndRef:
            builder = self.assemble_builder()
            stx = self.sign_builder(builder)
            my_key = self.our_identity.owning_key
            parties = []
            seen = set()
            for p in self.state_and_ref.state.data.participants:
                party = p if isinstance(p, Party) else (
                    self.services.identity_service
                    .well_known_party_from_anonymous(p)
                )
                if party is None:
                    raise FlowException(
                        "cannot resolve a participant to a well-known party"
                    )
                if party.owning_key == my_key or party.owning_key in seen:
                    continue
                seen.add(party.owning_key)
                parties.append(party)
            sessions = [self.initiate_flow(p) for p in parties]
            stx = self.sub_flow(CollectSignaturesFlow(stx, sessions))
            final = self.sub_flow(FinalityFlow(stx))
            from corda_tpu.ledger import StateRef

            return StateAndRef(final.tx.outputs[0], StateRef(final.id, 0))

    class Acceptor(SignTransactionFlow):
        """Participants approve structurally-valid replacements; the ledger
        special-form verification (LedgerTransaction._verify_notary_change /
        _verify_contract_upgrade) already ran inside
        ReceiveTransactionFlow."""


class NotaryChangeFlow(AbstractStateReplacementFlow.Instigator):
    """Re-point a state at a new notary (reference: NotaryChangeFlow.kt)."""

    def __init__(self, state_and_ref: StateAndRef, new_notary: Party):
        super().__init__(state_and_ref)
        self.new_notary = new_notary

    def flow_fields(self):
        return {"state_and_ref": self.state_and_ref,
                "new_notary": self.new_notary}

    @classmethod
    def from_flow_fields(cls, fields):
        return cls(fields["state_and_ref"], fields["new_notary"])

    def assemble_builder(self) -> TransactionBuilder:
        ts = self.state_and_ref.state
        signers = [
            p.owning_key for p in ts.data.participants
        ]
        b = TransactionBuilder(notary=ts.notary)
        b.add_input_state(self.state_and_ref)
        b.add_output_state(ts.data, ts.contract, notary=self.new_notary,
                           encumbrance=ts.encumbrance,
                           constraint=ts.constraint)
        b.add_command(NotaryChangeCommand(self.new_notary), *signers)
        return b


@InitiatedBy(NotaryChangeFlow)
class NotaryChangeAcceptor(AbstractStateReplacementFlow.Acceptor):
    def check_transaction(self, stx: SignedTransaction) -> None:
        ltx = self.services.resolve_to_ledger_transaction(stx)
        if not ltx.commands_of_type(NotaryChangeCommand):
            raise FlowException("expected a notary-change transaction")


class ContractUpgradeFlow(AbstractStateReplacementFlow.Instigator):
    """Upgrade a state to a new contract version (reference:
    ContractUpgradeFlow.kt). ``new_contract`` is the registered name of a
    contract class declaring ``legacy_contract`` and ``upgrade(state)``."""

    def __init__(self, state_and_ref: StateAndRef, new_contract: str):
        super().__init__(state_and_ref)
        self.new_contract = new_contract

    def flow_fields(self):
        return {"state_and_ref": self.state_and_ref,
                "new_contract": self.new_contract}

    @classmethod
    def from_flow_fields(cls, fields):
        return cls(fields["state_and_ref"], fields["new_contract"])

    def assemble_builder(self) -> TransactionBuilder:
        from corda_tpu.ledger import resolve_contract

        ts = self.state_and_ref.state
        new_cls = resolve_contract(self.new_contract)
        upgraded = new_cls.upgrade(ts.data)
        signers = [p.owning_key for p in ts.data.participants]
        b = TransactionBuilder(notary=ts.notary)
        b.add_input_state(self.state_and_ref)
        b.add_output_state(upgraded, self.new_contract,
                           encumbrance=ts.encumbrance,
                           constraint=ts.constraint)
        b.add_command(UpgradeCommand(self.new_contract), *signers)
        return b


@InitiatedBy(ContractUpgradeFlow)
class ContractUpgradeAcceptor(AbstractStateReplacementFlow.Acceptor):
    def check_transaction(self, stx: SignedTransaction) -> None:
        ltx = self.services.resolve_to_ledger_transaction(stx)
        if not ltx.commands_of_type(UpgradeCommand):
            raise FlowException("expected a contract-upgrade transaction")
