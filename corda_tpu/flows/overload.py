"""Overload governor: deadline propagation, retry budgets, adaptive admission.

A node past its knee fails in a characteristic, *metastable* way: the
flow engine queues unboundedly, every queued flow still burns full
verify/notary work after its caller has given up, and a partition heal
releases a synchronized retransmit storm with no aggregate bound — load
sheds nothing, goodput collapses to zero, and the collapse outlives the
burst that caused it. This module is the floor under that failure mode
(docs/OVERLOAD.md), three mechanisms sharing one governor:

- **end-to-end deadline propagation** — a wall-clock deadline born at
  ``start_flow(deadline_s=...)`` rides the executor, the ``SessionInit``
  wire message (old payloads decode — the field is omitted when unset,
  so the off path adds zero wire bytes), and a thread-local
  ``deadline_scope`` that downstream stages read: the serving scheduler
  derives its queue-shed deadline from it, the notary front door and
  flush window drop already-dead requests, and the Raft/BFT clients
  bound their submit budgets by it. Dead work is shed at the *earliest*
  stage that notices — goodput, not throughput;

- **retry budgets** — a token bucket per (layer, peer edge): fresh
  sends earn ``retry_ratio`` tokens, retries spend one, so aggregate
  retry volume is capped at a fraction of fresh traffic however many
  individual backoff clocks align. Consumes PR 15's
  ``net.partition_suspect`` events to pre-emptively widen session
  retransmit backoff on a suspected edge (a healed edge drains instead
  of storming);

- **adaptive admission** — an AIMD concurrency limit on in-flight
  flows keyed to the measured flow p99 vs the configured SLO:
  breaching windows multiply the limit down, healthy windows add to
  it. Rejection is fail-fast (``FlowAdmissionError`` raised before any
  checkpoint write) and brownout-ordered: per-class headroom shares
  mean BULK is shed first, then SERVICE, INTERACTIVE last — mirroring
  the serving scheduler's priority classes. Rejects observe into the
  SLO window as errors with NO latency sample (the PR 7 pin), so a
  browned-out node never reads as a perfect p99.

Off by default, the PR 7/14 convention: every hook calls
``active_overload()`` (two attribute reads when off after a one-time
``CORDA_TPU_OVERLOAD=1`` env probe), ``configure_overload()`` flips it
programmatically, and while disabled the process registry gains no
``overload.*``/``retry_budget.*``/``admission.*`` names, no threads, and
no wire bytes. Fault sites ``overload.admission`` and
``retry.budget_exhausted`` let the chaos fabric force rejections and
budget exhaustion deterministically (docs/FAULT_INJECTION.md).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import OrderedDict, deque

# serving-scheduler priority class names, mirrored as literals so this
# module never imports the serving package (the scheduler imports us)
INTERACTIVE = "interactive"
SERVICE = "service"
BULK = "bulk"

# brownout order: the fraction of the admission limit each class may
# fill. BULK hits its ceiling first (sheds first), INTERACTIVE holds the
# full limit (sheds last) — the same reserved-share idea as the serving
# scheduler's _RESERVED, pointed at admission instead of batch assembly.
_DEFAULT_CLASS_SHARES = {INTERACTIVE: 1.0, SERVICE: 0.85, BULK: 0.6}


class FlowAdmissionError(Exception):
    """Adaptive admission rejected the flow at ``start_flow`` — raised
    BEFORE any checkpoint write or span/profile registration, so a
    rejection costs the caller one exception and the node nothing
    durable. Callers shed, degrade, or retry against their own budget."""


# ------------------------------------------------------- deadline scope
#
# The cross-layer carrier for a propagated deadline: an absolute
# wall-clock (epoch) instant, set for the duration of a flow's execution
# segment by the engine and read by any downstream stage on the same
# thread (serving submit, notary request, consensus client submit).
# Wall-clock on purpose — the deadline crosses nodes in SessionInit, and
# monotonic clocks do not travel.

_tls = threading.local()


@contextlib.contextmanager
def deadline_scope(deadline_t: float | None):
    """Bind ``deadline_t`` (epoch seconds, or None) as the calling
    thread's propagated deadline for the duration of the block."""
    prev = getattr(_tls, "deadline_t", None)
    _tls.deadline_t = deadline_t
    try:
        yield
    finally:
        _tls.deadline_t = prev


def current_deadline_t() -> float | None:
    """The propagated absolute deadline bound to this thread, or None."""
    return getattr(_tls, "deadline_t", None)


def remaining_deadline() -> float | None:
    """Seconds until the propagated deadline (may be <= 0 once expired),
    or None when no deadline is in scope. One thread-local read — cheap
    enough for every submit path to call unconditionally."""
    t = getattr(_tls, "deadline_t", None)
    if t is None:
        return None
    return t - time.time()


# ------------------------------------------------------------- governor


class _Bucket:
    """One (layer, edge) retry token bucket. Guarded by the governor's
    lock."""

    __slots__ = ("tokens", "granted", "denied")

    def __init__(self, initial: float):
        self.tokens = initial
        self.granted = 0
        self.denied = 0


class OverloadGovernor:
    """The process-wide overload policy: admission AIMD + retry token
    buckets + partition-suspect state. All hooks are O(1) under one
    lock; the clock is injectable so AIMD windows are testable without
    sleeping."""

    BUCKET_CAP = 1024   # bounded: a hostile peer set cannot grow memory
    LAT_WINDOW = 512    # recent flow latencies feeding the AIMD signal

    def __init__(self, *, clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self._enabled = False
        self._gauges_registered = False
        # ---- adaptive admission (AIMD on in-flight flows)
        self.slo_p99_s = 1.0          # the latency target the limit chases
        self.min_limit = 4.0
        self.max_limit = 4096.0
        self.limit = 64.0             # current concurrency ceiling
        self.increase = 1.0           # additive raise per healthy window
        self.decrease = 0.7           # multiplicative cut per breach
        self.adapt_interval_s = 0.25
        self.adapt_min_samples = 8
        self.class_shares = dict(_DEFAULT_CLASS_SHARES)
        self._inflight = 0
        self._last_adapt = 0.0
        self._lat_window: deque = deque(maxlen=self.LAT_WINDOW)
        self.admitted = 0
        self.rejected = 0
        self.rejected_by_class: dict[str, int] = {}
        self.deadline_shed = 0
        # ---- retry budgets (token bucket per layer+edge)
        self.retry_ratio = 0.5        # tokens earned per fresh send
        self.retry_burst = 32.0       # bucket cap
        self.retry_initial = 2.0      # allowance before any fresh send
        self._buckets: OrderedDict = OrderedDict()
        self.fresh_sends: dict[str, int] = {}   # per layer
        self.retry_granted = 0
        self.retry_denied = 0
        # ---- partition suspicion (netstats consumption)
        self.suspect_backoff_scale = 4.0
        self._suspect_edges: set[str] = set()
        self._last_net_sync = 0.0

    # ------------------------------------------------------------- lifecycle
    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def reset(self) -> None:
        with self._lock:
            self._inflight = 0
            self._lat_window.clear()
            self._last_adapt = 0.0
            self.admitted = 0
            self.rejected = 0
            self.rejected_by_class = {}
            self.deadline_shed = 0
            self._buckets.clear()
            self.fresh_sends = {}
            self.retry_granted = 0
            self.retry_denied = 0
            self._suspect_edges.clear()
            self._last_net_sync = 0.0

    def _ensure_gauges(self) -> None:
        # registered lazily from the first live hook, never while off —
        # the fresh-subprocess pin holds: overload off means NO names
        if self._gauges_registered:
            return
        self._gauges_registered = True
        from corda_tpu.node.monitoring import node_metrics

        m = node_metrics()
        m.gauge("admission.inflight", lambda: self._inflight)
        m.gauge("overload.limit", lambda: self.limit)

    # ------------------------------------------------------------- admission
    def try_admit(self, priority: str = SERVICE) -> bool:
        """Admission decision for one flow start. Counts both verdicts;
        a rejection observes into the SLO window as an error with no
        latency sample (the PR 7 pin extended to admission)."""
        self._ensure_gauges()
        forced = False
        from corda_tpu.faultinject import InjectedFault, check_site

        try:
            check_site("overload.admission")
        except InjectedFault:
            forced = True  # the plan forces this admission to reject
        with self._lock:
            share = self.class_shares.get(
                priority, self.class_shares.get(SERVICE, 0.85)
            )
            if forced or self._inflight >= self.limit * share:
                self.rejected += 1
                self.rejected_by_class[priority] = (
                    self.rejected_by_class.get(priority, 0) + 1
                )
                admitted = False
            else:
                self._inflight += 1
                self.admitted += 1
                admitted = True
        c = _ov_counters()
        if admitted:
            c["admitted"].inc()
            return True
        c["rejected"].inc()
        from corda_tpu.observability.slo import active_slo

        slo = active_slo()
        if slo is not None:
            # error with NO latency sample: the flow never ran, and an
            # instant reject must not read as a perfect p99
            slo.observe(priority, None, error=True)
        return False

    def release(self, priority: str, latency_s: float | None,
                *, error: bool = False) -> None:
        """One admitted flow finished (either way): free its slot, feed
        the AIMD latency window, adapt the limit on interval."""
        now = self._clock()
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            if latency_s is not None and not error:
                self._lat_window.append((now, latency_s))
            self._adapt_locked(now)

    def _adapt_locked(self, now: float) -> None:
        if now - self._last_adapt < self.adapt_interval_s:
            return
        self._last_adapt = now
        horizon = now - max(1.0, 8 * self.adapt_interval_s)
        lats = sorted(lat for t, lat in self._lat_window if t >= horizon)
        if len(lats) < self.adapt_min_samples:
            return
        p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))]
        if p99 > self.slo_p99_s:
            self.limit = max(self.min_limit, self.limit * self.decrease)
        else:
            self.limit = min(self.max_limit, self.limit + self.increase)

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    # -------------------------------------------------------- deadline sheds
    def note_deadline_shed(self, priority: str = SERVICE,
                           latency_s: float | None = None) -> None:
        """Downstream stage dropped already-dead work. Observes into the
        SLO window as an error (with the elapsed wall when the caller
        knows it) so propagated-deadline sheds never hide from p99."""
        with self._lock:
            self.deadline_shed += 1
        _ov_counters()["deadline_shed"].inc()
        from corda_tpu.observability.slo import active_slo

        slo = active_slo()
        if slo is not None:
            slo.observe(priority, latency_s, error=True)

    # --------------------------------------------------------- retry budgets
    def note_send(self, layer: str, edge: str) -> None:
        """A FRESH send on (layer, edge) earns ``retry_ratio`` tokens."""
        with self._lock:
            self.fresh_sends[layer] = self.fresh_sends.get(layer, 0) + 1
            b = self._bucket_locked(layer, edge)
            b.tokens = min(self.retry_burst, b.tokens + self.retry_ratio)

    def budget_earned(self) -> float:
        """Total retry budget ever earned (initial allowance per live
        bucket + ratio × fresh sends): ``retry_granted <= budget_earned``
        is the counter-reconciled budget property the metastability gate
        checks."""
        with self._lock:
            return (self.retry_initial * max(1, len(self._buckets))
                    + self.retry_ratio * sum(self.fresh_sends.values()))

    def allow_retry(self, layer: str, edge: str) -> bool:
        """Spend one retry token for (layer, edge). Denied retries are
        counted; the ``retry.budget_exhausted`` fault site lets a chaos
        plan force exhaustion at this exact decision."""
        self._ensure_gauges()
        forced = False
        from corda_tpu.faultinject import InjectedFault, check_site

        try:
            check_site("retry.budget_exhausted")
        except InjectedFault:
            forced = True
        with self._lock:
            b = self._bucket_locked(layer, edge)
            if forced or b.tokens < 1.0:
                b.denied += 1
                self.retry_denied += 1
                granted = False
            else:
                b.tokens -= 1.0
                b.granted += 1
                self.retry_granted += 1
                granted = True
        c = _ov_counters()
        if granted:
            c["retry_granted"].inc()
        else:
            c["retry_denied"].inc()
        return granted

    def _bucket_locked(self, layer: str, edge: str) -> _Bucket:
        key = (layer, edge)
        b = self._buckets.get(key)
        if b is None:
            if len(self._buckets) >= self.BUCKET_CAP:
                self._buckets.popitem(last=False)
            b = self._buckets[key] = _Bucket(self.retry_initial)
        return b

    # ------------------------------------------------- partition suspicion
    def sync_net_events(self) -> None:
        """Consume the netstats event ring: rebuild the suspected-edge
        set from each edge's LAST suspect/healed event. Rate-limited;
        never called under any other lock (netstats takes its own)."""
        now = self._clock()
        with self._lock:
            if now - self._last_net_sync < 0.25:
                return
            self._last_net_sync = now
        from corda_tpu.messaging.netstats import active_netstats

        n = active_netstats()
        if n is None:
            return
        n.check_partitions()
        verdict: dict[str, bool] = {}
        for ev in list(n.events):
            kind = ev.get("kind")
            if kind == "net.partition_suspect":
                verdict[ev["edge"]] = True
            elif kind == "net.partition_healed":
                verdict[ev["edge"]] = False
        suspects = {edge for edge, bad in verdict.items() if bad}
        with self._lock:
            self._suspect_edges = suspects

    def edge_suspected(self, src: str, dst: str) -> bool:
        with self._lock:
            return f"{src}->{dst}" in self._suspect_edges

    # -------------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        with self._lock:
            buckets = {
                f"{layer}:{edge}": {
                    "tokens": b.tokens, "granted": b.granted,
                    "denied": b.denied,
                }
                for (layer, edge), b in self._buckets.items()
            }
            return {
                "enabled": self._enabled,
                "limit": self.limit,
                "inflight": self._inflight,
                "slo_p99_s": self.slo_p99_s,
                "class_shares": dict(self.class_shares),
                "admitted": self.admitted,
                "rejected": self.rejected,
                "rejected_by_class": dict(self.rejected_by_class),
                "deadline_shed": self.deadline_shed,
                "retry_ratio": self.retry_ratio,
                "retry_initial": self.retry_initial,
                "fresh_sends": dict(self.fresh_sends),
                "budget_earned": (
                    self.retry_initial * max(1, len(self._buckets))
                    + self.retry_ratio * sum(self.fresh_sends.values())
                ),
                "retry_granted": self.retry_granted,
                "retry_denied": self.retry_denied,
                "buckets": buckets,
                "suspect_edges": sorted(self._suspect_edges),
            }


# ------------------------------------------------------- metric registration
#
# Every overload.*/retry_budget.*/admission.* metric name appears here
# (or in _ensure_gauges) as a LITERAL so the metrics-doc lint enumerates
# them and enforces their docs/OBSERVABILITY.md rows. Called only from
# live hooks — while the governor is off the process registry gains no
# overload names at all.

def _ov_counters() -> dict:
    from corda_tpu.node.monitoring import node_metrics

    m = node_metrics()
    return {
        "admitted": m.counter("overload.admitted"),
        "rejected": m.counter("overload.rejected"),
        "deadline_shed": m.counter("overload.deadline_shed"),
        "retry_granted": m.counter("retry_budget.granted"),
        "retry_denied": m.counter("retry_budget.denied"),
    }


# --------------------------------------------------- process-global registry

_global = OverloadGovernor()
_env_checked = False


def overload_governor() -> OverloadGovernor:
    return _global


def active_overload() -> OverloadGovernor | None:
    """The hot-path check every hook performs: the process governor when
    overload protection is ON, else None. Two attribute reads when off
    (after the one-time env probe)."""
    global _env_checked
    if not _env_checked:
        _env_checked = True
        if os.environ.get("CORDA_TPU_OVERLOAD", "") == "1":
            _global.enable()
    g = _global
    return g if g._enabled else None


def configure_overload(*, enabled: bool | None = None, reset: bool = False,
                       limit: float | None = None,
                       min_limit: float | None = None,
                       max_limit: float | None = None,
                       slo_p99_s: float | None = None,
                       retry_ratio: float | None = None,
                       retry_burst: float | None = None,
                       retry_initial: float | None = None,
                       suspect_backoff_scale: float | None = None,
                       class_shares: dict | None = None,
                       ) -> OverloadGovernor:
    """The overload knob (docs/OVERLOAD.md): flip the governor on/off,
    seed the AIMD limit and SLO target, size the retry buckets.
    ``reset`` drops every counter, bucket, and the latency window. The
    ``CORDA_TPU_OVERLOAD=1`` env knob enables it at first hook touch
    without code changes."""
    global _env_checked
    _env_checked = True  # explicit configuration overrides the env probe
    if reset:
        _global.reset()
    if limit is not None:
        _global.limit = float(limit)
    if min_limit is not None:
        _global.min_limit = float(min_limit)
    if max_limit is not None:
        _global.max_limit = float(max_limit)
    if slo_p99_s is not None:
        _global.slo_p99_s = float(slo_p99_s)
    if retry_ratio is not None:
        _global.retry_ratio = float(retry_ratio)
    if retry_burst is not None:
        _global.retry_burst = float(retry_burst)
    if retry_initial is not None:
        _global.retry_initial = float(retry_initial)
    if suspect_backoff_scale is not None:
        _global.suspect_backoff_scale = float(suspect_backoff_scale)
    if class_shares is not None:
        _global.class_shares = dict(class_shares)
    if enabled is not None:
        if enabled:
            _global.enable()
        else:
            _global.disable()
    return _global


def overload_section() -> dict:
    """The ``overload`` section of monitoring/flight snapshots: the full
    governor snapshot while on, a bare disabled marker while off."""
    g = _global
    if not g._enabled:
        return {"enabled": False}
    return g.snapshot()
