"""Checkpoint storage: the persisted op log per flow.

The role of DBCheckpointStorage (node/.../services/persistence/
DBCheckpointStorage.kt:16) — but a checkpoint here is not an opaque
serialized fiber stack: it is (flow class + constructor args) plus the
ordered list of recorded op results. Writing op N's result and making its
effect durable happen in one sqlite transaction — the equivalent of the
reference's checkpoint-commit riding the message-ack DB transaction
(StateMachineManager.kt:548, FlowStateMachineImpl.kt:466-477).
"""

from __future__ import annotations

import sqlite3
import threading
from collections import deque

from corda_tpu.serialization import deserialize, serialize


class CheckpointStorage:
    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS flows (
                 flow_id TEXT PRIMARY KEY,
                 flow_blob BLOB NOT NULL,      -- CBE (class name, args)
                 our_name TEXT NOT NULL,
                 started_at REAL NOT NULL
               )"""
        )
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS oplog (
                 flow_id TEXT NOT NULL,
                 op_index INTEGER NOT NULL,
                 result_blob BLOB NOT NULL,
                 PRIMARY KEY (flow_id, op_index)
               )"""
        )
        # the persisted processed-message table (reference:
        # NodeMessagingClient.kt:187 — dedupe must survive restarts, or a
        # redelivered SessionInit after the responder completed would spawn
        # a second responder). ``rid`` orders entries so the table trims
        # FIFO like the broker's duplicate-ID cache instead of growing for
        # the node's lifetime; pre-existing databases with the older
        # two-column schema keep working (inserts name their columns, the
        # trim no-ops without ``rid``).
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS processed_inits (
                 rid INTEGER PRIMARY KEY AUTOINCREMENT,
                 msg_id TEXT UNIQUE,
                 flow_id TEXT NOT NULL
               )"""
        )
        self._db.commit()
        self._inits_since_trim = 0

    INITS_CACHE_MAX = 100_000

    # ------------------------------------------------------------- flows
    def add_flow(self, flow_id: str, flow_blob: bytes, our_name: str,
                 started_at: float) -> None:
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO flows VALUES (?,?,?,?)",
                (flow_id, flow_blob, our_name, started_at),
            )
            self._db.commit()

    def remove_flow(self, flow_id: str) -> None:
        """Flow finished: checkpoint and op log drop atomically."""
        with self._lock:
            self._db.execute("DELETE FROM flows WHERE flow_id=?", (flow_id,))
            self._db.execute("DELETE FROM oplog WHERE flow_id=?", (flow_id,))
            self._db.commit()

    def all_flows(self) -> list[tuple[str, bytes, str, float]]:
        """Checkpointed flows in deterministic (started_at, flow_id) order
        — restore after a crash replays flows in a stable sequence, so a
        restart under chaos reproduces rather than reshuffles."""
        with self._lock:
            return list(
                self._db.execute(
                    "SELECT flow_id, flow_blob, our_name, started_at "
                    "FROM flows ORDER BY started_at, flow_id"
                )
            )

    def get_flow(self, flow_id: str) -> bytes | None:
        """The flow blob for one checkpointed flow (the park/resume path
        rebuilds a single flow without scanning the table)."""
        with self._lock:
            row = self._db.execute(
                "SELECT flow_blob FROM flows WHERE flow_id=?", (flow_id,)
            ).fetchone()
            return row[0] if row else None

    # ------------------------------------------------------------- op log
    def record_op(self, flow_id: str, op_index: int, result) -> None:
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO oplog VALUES (?,?,?)",
                (flow_id, op_index, serialize(result)),
            )
            self._db.commit()

    def load_oplog(self, flow_id: str) -> list:
        with self._lock:
            rows = self._db.execute(
                "SELECT op_index, result_blob FROM oplog WHERE flow_id=? "
                "ORDER BY op_index",
                (flow_id,),
            ).fetchall()
        # guard against holes (should not happen; fail loudly if they do)
        for expect, (idx, _) in enumerate(rows):
            if idx != expect:
                raise RuntimeError(
                    f"op log hole for flow {flow_id}: expected {expect}, got {idx}"
                )
        return [deserialize(blob) for _, blob in rows]

    # ---------------------------------------------------------- init dedupe
    def mark_init_processed(self, msg_id: str, flow_id: str) -> bool:
        """True if this call claimed the init; False if already processed."""
        with self._lock:
            cur = self._db.execute(
                "INSERT OR IGNORE INTO processed_inits (msg_id, flow_id) "
                "VALUES (?,?)",
                (msg_id, flow_id),
            )
            self._inits_since_trim += 1
            if self._inits_since_trim >= 4096:
                self._inits_since_trim = 0
                try:
                    self._db.execute(
                        """DELETE FROM processed_inits WHERE rid <=
                             (SELECT MAX(rid) FROM processed_inits) - ?""",
                        (self.INITS_CACHE_MAX,),
                    )
                except sqlite3.OperationalError:
                    pass  # legacy schema without rid: unbounded as before
            self._db.commit()
            return cur.rowcount == 1

    def mark_init_rejected(self, msg_id: str, reason: str) -> None:
        """Re-mark a claimed init as rejected (``rejected:<reason>``), so a
        retransmitted init of a rejected open repeats the rejection rather
        than being mistaken for a completed responder."""
        with self._lock:
            self._db.execute(
                "UPDATE processed_inits SET flow_id=? WHERE msg_id=?",
                (f"rejected:{reason}", msg_id),
            )
            self._db.commit()

    def init_flow_id(self, msg_id: str) -> str | None:
        with self._lock:
            row = self._db.execute(
                "SELECT flow_id FROM processed_inits WHERE msg_id=?",
                (msg_id,),
            ).fetchone()
            return row[0] if row else None

    def close(self) -> None:
        with self._lock:
            self._db.close()


class WalCheckpointStorage:
    """``CheckpointStorage``'s API over the crash-consistent durability
    tier (docs/DURABILITY.md): flow checkpoints, the per-flow op log and
    the processed-inits dedupe table live in memory, journaled through a
    ``DurableStore`` WAL with group-commit fsync. Every mutation is
    durable BEFORE the call returns — ``record_op`` in particular flushes
    before the engine acks the consumed session message, which is exactly
    the reference's checkpoint-commit-rides-the-ack-transaction guarantee
    under a real crash model (the ``durability-ack-order`` lint pins the
    ordering). Recovery = newest snapshot + WAL replay; a restarted
    ``StateMachineManager.restore()`` then replays each flow's op log to
    its live point, so in-flight sessions resume (or deterministically
    abort via the session retry deadline) and SessionAck retransmission
    picks up from the durable sequence."""

    INITS_CACHE_MAX = CheckpointStorage.INITS_CACHE_MAX

    def __init__(self, store):
        self._store = store
        self._lock = threading.Lock()
        self._flows: dict[str, tuple[bytes, str, float]] = {}
        self._oplog: dict[str, dict[int, bytes]] = {}
        self._inits: dict[str, str] = {}
        self._inits_order: deque[str] = deque()
        # LSN of the last record the in-memory state reflects, updated
        # under the same lock as every append: a snapshot claims
        # coverage of exactly what its locked capture saw
        self._last_lsn = -1
        self.last_recovery = store.recover(self._apply, self._load_snapshot)
        self._last_lsn = max(self._last_lsn, store.wal.durable_lsn)

    # ------------------------------------------------------------ recovery
    def _apply(self, rec: dict) -> None:
        with self._lock:
            self._apply_locked(rec)

    def _apply_locked(self, rec: dict) -> None:
        k = rec["k"]
        if k == "flow":
            self._flows[rec["id"]] = (rec["blob"], rec["name"], rec["ts"])
        elif k == "op":
            self._oplog.setdefault(rec["id"], {})[rec["i"]] = rec["blob"]
        elif k == "rm":
            self._flows.pop(rec["id"], None)
            self._oplog.pop(rec["id"], None)
        elif k == "init":
            # first claim wins (INSERT OR IGNORE semantics) — a replayed
            # duplicate claim must not steal the original's flow id
            if rec["m"] not in self._inits:
                self._inits[rec["m"]] = rec["id"]
                self._inits_order.append(rec["m"])
        elif k == "rej":
            self._inits[rec["m"]] = f"rejected:{rec['r']}"
        self._trim_inits_locked()

    def _trim_inits_locked(self) -> None:
        while len(self._inits_order) > self.INITS_CACHE_MAX:
            self._inits.pop(self._inits_order.popleft(), None)

    def _load_snapshot(self, snap: dict) -> None:
        with self._lock:
            for fid, blob, name, ts in snap["flows"]:
                self._flows[fid] = (blob, name, ts)
            for fid, idx, blob in snap["oplog"]:
                self._oplog.setdefault(fid, {})[idx] = blob
            for msg_id, fid in snap["inits"]:
                if msg_id not in self._inits:
                    self._inits[msg_id] = fid
                    self._inits_order.append(msg_id)

    def _snapshot_state_locked(self) -> dict:
        return {
            "flows": [
                (fid, blob, name, ts)
                for fid, (blob, name, ts) in self._flows.items()
            ],
            "oplog": [
                (fid, idx, blob)
                for fid, ops in self._oplog.items()
                for idx, blob in sorted(ops.items())
            ],
            "inits": [(m, self._inits[m]) for m in self._inits_order],
        }

    def _maybe_snapshot(self) -> None:
        if self._store.snapshot_due():
            with self._lock:
                state = self._snapshot_state_locked()
                lsn = self._last_lsn
            self._store.snapshot(state, covered_lsn=lsn)

    # ------------------------------------------------------------- flows
    def add_flow(self, flow_id: str, flow_blob: bytes, our_name: str,
                 started_at: float) -> None:
        with self._lock:
            self._flows[flow_id] = (flow_blob, our_name, started_at)
            self._last_lsn = self._store.append(
                {"k": "flow", "id": flow_id, "blob": flow_blob,
                 "name": our_name, "ts": started_at})
        self._store.flush()
        self._maybe_snapshot()

    def remove_flow(self, flow_id: str) -> None:
        """Flow finished: checkpoint and op log drop atomically (one WAL
        record covers both)."""
        with self._lock:
            self._flows.pop(flow_id, None)
            self._oplog.pop(flow_id, None)
            self._last_lsn = self._store.append({"k": "rm", "id": flow_id})
        self._store.flush()
        self._maybe_snapshot()

    def all_flows(self) -> list[tuple[str, bytes, str, float]]:
        with self._lock:
            rows = [
                (fid, blob, name, ts)
                for fid, (blob, name, ts) in self._flows.items()
            ]
        return sorted(rows, key=lambda r: (r[3], r[0]))

    def get_flow(self, flow_id: str) -> bytes | None:
        with self._lock:
            row = self._flows.get(flow_id)
            return row[0] if row else None

    # ------------------------------------------------------------- op log
    def record_op(self, flow_id: str, op_index: int, result) -> None:
        blob = serialize(result)
        with self._lock:
            self._oplog.setdefault(flow_id, {})[op_index] = blob
            self._last_lsn = self._store.append(
                {"k": "op", "id": flow_id, "i": op_index, "blob": blob})
        # durable before the caller acks the message the op consumed
        self._store.flush()
        self._maybe_snapshot()

    def load_oplog(self, flow_id: str) -> list:
        with self._lock:
            rows = sorted(self._oplog.get(flow_id, {}).items())
        for expect, (idx, _) in enumerate(rows):
            if idx != expect:
                raise RuntimeError(
                    f"op log hole for flow {flow_id}: expected {expect}, got {idx}"
                )
        return [deserialize(blob) for _, blob in rows]

    # ---------------------------------------------------------- init dedupe
    def mark_init_processed(self, msg_id: str, flow_id: str) -> bool:
        with self._lock:
            if msg_id in self._inits:
                return False
            self._inits[msg_id] = flow_id
            self._inits_order.append(msg_id)
            self._trim_inits_locked()
            self._last_lsn = self._store.append(
                {"k": "init", "m": msg_id, "id": flow_id})
        self._store.flush()
        self._maybe_snapshot()
        return True

    def mark_init_rejected(self, msg_id: str, reason: str) -> None:
        with self._lock:
            self._inits[msg_id] = f"rejected:{reason}"
            self._last_lsn = self._store.append(
                {"k": "rej", "m": msg_id, "r": reason})
        self._store.flush()

    def init_flow_id(self, msg_id: str) -> str | None:
        with self._lock:
            return self._inits.get(msg_id)

    def close(self) -> None:
        self._store.flush()
        self._store.close()
