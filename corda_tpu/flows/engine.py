"""The flow state machine engine.

Role parity with StateMachineManager + FlowStateMachineImpl
(node/.../services/statemachine/StateMachineManager.kt:76-565,
FlowStateMachineImpl.kt:40-510), mechanism re-designed for deterministic
replay (package docstring):

- flows execute ``FlowLogic.call()`` from the top on a BOUNDED worker
  pool (reference: the single scheduler thread multiplexing thousands of
  Quasar fibers, StateMachineManager.kt:76-83) — never one OS thread per
  flow;
- each effectful op is numbered; its result is recorded via
  ``CheckpointStorage.record_op`` the moment it completes;
- on restore, recorded ops replay instantly (re-registering sessions,
  re-sending messages under their original deterministic ids — recipients
  dedupe), and execution turns live at the first unrecorded op;
- inbound session messages are acked only once consumed into the op log, so
  an at-least-once transport (messaging.queue) yields exactly-once effects —
  the guarantee the reference gets from checkpoint-commit riding the ack
  transaction (StateMachineManager.kt:548).

**Parking = the fiber mechanism.** Where Quasar captures a fiber's stack,
this engine PARKS a blocked flow: if a wait (receive, session confirm,
sleep, ledger commit) isn't satisfied within a short grace window, the
flow abandons its worker thread and registers a wake key; when the key
fires (message arrival, commit, deadline) the flow is re-queued and
REPLAYED from its op log to the wait point — an in-process crash+restore,
which the op-log design makes exact and cheap (each recorded op replays in
microseconds). A parked flow costs a dict entry, not a thread, so tens of
thousands of concurrent flows run on a fixed-size pool.

Session ids are derived ``sha256(flow_id ‖ op_index)`` so a crash-replayed
open reuses the same id.
"""

from __future__ import annotations

import hashlib
import random
import secrets
import threading
import time
from collections import deque
from concurrent.futures import Future

from corda_tpu.ledger import Party
from corda_tpu.messaging.queue import Message
from corda_tpu.messaging.retry import RetryPolicy
from corda_tpu.observability import (
    NOOP_SPAN,
    SPAN_FLOW,
    SPAN_FLOW_RESPONDER,
    TraceContext,
    tracer,
)
from corda_tpu.observability.cluster import active_cluster
from corda_tpu.observability.contention import register_wait_site
from corda_tpu.observability.flowprof import active_flowprof, flowprof_frame
from corda_tpu.observability.trace import current_trace_id
from corda_tpu.serialization import deserialize, serialize

# the sampler's blocked/running classifier (concurrency observatory):
# a thread sampled inside these functions is waiting on the SMM monitor
# — an idle worker, a flow blocked in wait_or_killed, the retransmit
# timer between scans — not burning CPU
register_wait_site("engine.py", "_worker_loop", "lock_wait")
register_wait_site("engine.py", "wait_or_killed", "lock_wait")

from .api import (
    FlowException,
    FlowLogic,
    FlowSession,
    class_path,
    load_class,
    rehydrate_flow_exception,
    responder_for,
)
from .checkpoints import CheckpointStorage
from .overload import (
    FlowAdmissionError,
    active_overload,
    deadline_scope,
)
from .sessions import (
    SESSION_TOPIC,
    SessionAck,
    SessionConfirm,
    SessionData,
    SessionEnd,
    SessionInit,
    SessionReject,
)


def _logical_id(msg_id: str) -> str:
    """Strip a retransmission suffix: retransmits travel under
    ``<base>~<attempt>`` so transport-level dedupe (which is per wire id)
    lets them through, while ALL protocol-level dedupe — the consumed set,
    responder-init claims, session acks — keys on the stable base id."""
    return msg_id.split("~", 1)[0]


class FlowKilledException(Exception):
    pass


class _FlowParked(BaseException):
    """Internal: the flow released its worker thread; it resumes by replay
    when its registered wake key fires.

    A BaseException so a flow's ``except Exception`` can never swallow the
    park signal. NOTE the unwind contract: parking raises THROUGH the flow
    body, so ``finally`` blocks run at park time and the try body re-runs
    on replay — "crash at the suspension point" semantics. Cleanup that
    must span a suspension (e.g. vault soft locks) therefore needs a
    replay hook re-establishing it (``FlowLogic.record(fn, replay=...)``)."""


_DEFAULT_RETRY_POLICY = RetryPolicy(
    base_s=0.25, multiplier=2.0, max_backoff_s=2.0, jitter=0.25,
    deadline_s=60.0,
)


class FlowHandle:
    def __init__(self, flow_id: str, result: Future):
        self.flow_id = flow_id
        self.result = result

    def __repr__(self):
        return f"FlowHandle({self.flow_id})"


def _sid_for(flow_id: str, op_index: int) -> int:
    h = hashlib.sha256(f"{flow_id}:{op_index}".encode()).digest()
    return int.from_bytes(h[:8], "big") | 1  # nonzero


class _SessionState:
    __slots__ = ("local_sid", "peer", "peer_sid", "inbound", "executor",
                 "rejected", "seq_out", "seq_enqueued", "seq_pending",
                 "gap_since", "gap_timer_armed")

    def __init__(self, local_sid: int, peer: Party, executor):
        self.local_sid = local_sid
        self.peer = peer
        self.peer_sid: int | None = None
        # ("data"|"end", payload/error, msg_id, ack, seq)
        self.inbound: deque = deque()
        self.executor = executor
        self.rejected: str | None = None
        # per-session ordered delivery (see SessionData.seq): outbound
        # messages are stamped 1, 2, ... from seq_out; inbound sequenced
        # messages move pending → inbound only in seq order, so a
        # delayed Data can never be overtaken by a later Data or the
        # End — the gap parks in seq_pending until the retransmit fills
        # it. Counters are restored from the oplog on crash replay
        # (op_send / op_receive records carry the seq). gap_since /
        # gap_timer_armed drive the liveness backstop: a gap older than
        # the session retry deadline can never fill (the sender gave
        # up), so _gap_check force-drains it rather than park the
        # receiving flow forever.
        self.seq_out: int = 0
        self.seq_enqueued: int = 0
        self.seq_pending: dict[int, tuple] = {}
        self.gap_since: float | None = None
        self.gap_timer_armed: bool = False


class _Retrans:
    """One unacknowledged outbound session message: retransmitted with
    exponential backoff + jitter until its SessionAck (or, for Init, the
    Confirm/Reject) arrives or the deadline expires — the flow-session
    half of at-least-once delivery over a transport that may drop."""

    __slots__ = ("base_id", "party_name", "payload", "kind", "sid",
                 "attempt", "backoff_s", "next_at", "deadline")

    def __init__(self, base_id: str, party_name: str, payload: bytes,
                 kind: str, sid: int, policy: RetryPolicy, rng,
                 deadline_s: float):
        self.base_id = base_id
        self.party_name = party_name
        self.payload = payload
        self.kind = kind            # "init" | "data"
        self.sid = sid              # LOCAL sid of the sending session
        self.attempt = 0
        self.backoff_s = policy.backoff_s(0, rng)
        self.next_at = time.monotonic() + self.backoff_s
        self.deadline = time.monotonic() + deadline_s


class _FlowExecutor:
    def __init__(self, smm: "StateMachineManager", flow_id: str,
                 oplog: list, flow: FlowLogic | None,
                 responder_cls: type | None = None,
                 init_info: dict | None = None,
                 result: Future | None = None):
        self.smm = smm
        self.flow_id = flow_id
        self.oplog = oplog
        self.flow = flow                      # None for responders until built
        self.responder_cls = responder_cls
        self.init_info = init_info            # live responder spawn only
        self.op_counter = 0
        # the result future OUTLIVES this executor: a parked flow resumes
        # on a fresh executor that resolves the same future
        self.result: Future = result if result is not None else Future()
        self.sessions: list[int] = []         # local sids owned
        self.killed = False                   # set by SMM.kill_flow
        # the flow's trace span (or NOOP when unsampled): spans the whole
        # flow lifetime across park/replay — a resumed flow's fresh
        # executor rebinds the SAME span from the SMM's span table
        self.trace_span = smm.span_of(flow_id)
        # the flow's phase-accounting ledger (flowprof), same lifetime
        # contract as the span: opened at flow start, rebound across
        # park/replay, closed in flow_finished
        fp = active_flowprof()
        self.prof_acct = fp.acct_of(flow_id) if fp is not None else None
        # propagated end-to-end deadline (docs/OVERLOAD.md): absolute
        # wall-clock epoch by which the caller stops caring, or None.
        # Set by start_flow (initiator), _handle_init (responder, off the
        # SessionInit wire field) and _rebuild (from checkpoint meta, so
        # the deadline survives park/replay and crash restore); bound as
        # the thread's deadline scope for every execution segment
        self.deadline_t: float | None = None

    # ------------------------------------------------------------ op core
    def _do_op(self, effect, replay=None):
        idx = self.op_counter
        self.op_counter += 1
        if idx < len(self.oplog):
            rec = self.oplog[idx]
            if replay is not None:
                replay(idx, rec)
            return rec
        rec = effect(idx)
        with flowprof_frame("checkpoint"):
            self.smm.checkpoints.record_op(self.flow_id, idx, rec)
        return rec

    # ------------------------------------------------------------ ops
    def op_entropy(self, n: int) -> bytes:
        return self._do_op(lambda idx: secrets.token_bytes(n))

    def op_record(self, fn, replay_fn=None):
        """Record fn()'s result; on replay, optionally run
        ``replay_fn(recorded)`` to re-establish host-side state the
        original call created (locks, registrations) — state that a park's
        unwind or a crash may have dropped."""
        replay = (
            (lambda idx, rec: replay_fn(rec)) if replay_fn is not None else None
        )
        return self._do_op(lambda idx: fn(), replay)

    def op_commit_pin(self) -> None:
        """Recorded op marking the flow's point of no return
        (FlowLogic.commit_pin): from here the propagated deadline stops
        shedding this flow (resume-time shed and retransmit-entry kill
        both check the pin). Recorded so crash restore re-establishes
        the pin from the oplog before any replay decision."""
        def effect(idx):
            self.smm._commit_pinned.add(self.flow_id)
            return {"commit_pin": True}

        self._do_op(
            effect,
            replay=lambda idx, rec: self.smm._commit_pinned.add(self.flow_id),
        )

    def _pinned(self) -> bool:
        return self.flow_id in self.smm._commit_pinned

    def op_sleep(self, seconds: float) -> None:
        rec = self._do_op(lambda idx: {"deadline": time.time() + seconds})
        remaining = rec["deadline"] - time.time()
        if remaining > 0:
            self.smm.wait_or_killed(
                lambda: False, timeout=remaining, executor=self,
                sleep_deadline=rec["deadline"],
            )

    def op_send(self, local_sid: int, obj) -> None:
        with flowprof_frame("serialize"):
            payload = serialize(obj)

        def effect(idx):
            # publish-then-record: a crash in between replays this op live
            # and re-publishes under the same deterministic msg id, which
            # the recipient's consumed-set dedupes. A *recorded* send was
            # durably enqueued, so replay never re-sends.
            seq = self._send_data(local_sid, payload, idx)
            return {"i": idx, "seq": seq}

        def replay(idx, rec):
            # replay never re-sends, so the session's outbound sequence
            # counter must be restored from the record — the next LIVE
            # send (and the finish-time End) continue the numbering the
            # peer has already seen
            seq = rec.get("seq", 0)
            if seq:
                sess = self.smm.session(local_sid)
                if seq > sess.seq_out:
                    sess.seq_out = seq

        self._do_op(effect, replay)

    def _retry_deadline_s(self) -> float | None:
        """Deadline propagation: a flow declaring ``retry_deadline_s``
        bounds every retransmit window it opens (sessions inherit the
        flow's budget); otherwise the SMM policy default applies. An
        end-to-end deadline tightens either further — retransmitting a
        message whose flow is already dead is pure storm fuel."""
        rem = None
        if self.deadline_t is not None and not self._pinned():
            # floor keeps the entry alive long enough for the deadline
            # pop to fail the session cleanly rather than instantly
            rem = max(0.05, self.deadline_t - time.time())
        flow_budget = getattr(self.flow, "retry_deadline_s", None)
        if flow_budget is None or self.smm._retry_policy is None:
            return rem
        out = min(flow_budget, self.smm._retry_policy.deadline_s)
        return out if rem is None else min(out, rem)

    def _send_data(self, local_sid: int, payload: bytes, idx: int) -> int:
        sess = self.smm.session(local_sid)
        if sess.peer_sid is None:
            raise FlowException("session not confirmed")
        sess.seq_out += 1
        self.smm.send_to(
            sess.peer, SessionData(sess.peer_sid, payload, sess.seq_out),
            msg_id=f"{self.flow_id}:op{idx}",
            track_kind="data", track_sid=local_sid,
            deadline_s=self._retry_deadline_s(),
        )
        return sess.seq_out

    def op_receive(self, local_sid: int):
        def effect(idx):
            sess = self.smm.session(local_sid)
            # deadline-aware wait: an unpinned flow whose end-to-end
            # deadline expires while it waits must shed, not hang — with
            # ordered delivery a permanently-lost message parks the
            # session (the End waits behind the gap too), so the wake
            # can no longer rely on SOMETHING eventually arriving. The
            # park path wakes via the sleeper timer and replays through
            # _run_body's shed; the on-thread path returns None here.
            dl = (self.deadline_t
                  if self.deadline_t is not None and not self._pinned()
                  else None)
            got = self.smm.wait_or_killed(
                lambda: sess.inbound[0] if sess.inbound else None,
                timeout=(None if dl is None
                         else max(0.0, dl - time.time())),
                executor=self, park_key=("sid", local_sid),
                sleep_deadline=dl,
            )
            if got is None:
                ov = active_overload()
                if ov is not None:
                    ov.note_deadline_shed(
                        str(getattr(self.flow, "priority", "service"))
                        if self.flow is not None else "service"
                    )
                raise FlowException("flow deadline exceeded")
            # pop + mark-consumed atomically: a retransmit landing between
            # the two would pass both dedupe checks (not buffered, not yet
            # consumed) and be re-buffered — a later receive would then
            # consume the stale duplicate as its own message
            kind, body, msg_id, ack, seq = self.smm.consume_inbound(sess)
            if kind == "end":
                rec = {"end": body if body else "peer ended session"}
            else:
                rec = {"payload": body, "msg_id": msg_id}
            if seq:
                # persisted so crash replay can restore the session's
                # delivery cursor (seq omitted when 0 — pre-sequencing
                # checkpoints keep their exact record shape)
                rec["seq"] = seq
            # record BEFORE ack: consumed-and-durable, then delete from queue
            with flowprof_frame("checkpoint"):
                self.smm.checkpoints.record_op(self.flow_id, idx, rec)
            if msg_id:
                # session-level ack: the peer's retransmit buffer settles;
                # a lost ack just means one more (deduped) retransmit
                self.smm.ack_session_msg(sess.peer, msg_id)
            if ack:
                ack()
            return rec

        idx = self.op_counter
        self.op_counter += 1
        if idx < len(self.oplog):
            rec = self.oplog[idx]
            seq = rec.get("seq", 0)
            if seq:
                # replayed receive: advance the delivery cursor so a NEW
                # message arriving post-restore (seq = cursor + 1) drains
                # instead of parking behind seqs consumed pre-crash
                sess = self.smm.session(local_sid)
                if seq > sess.seq_enqueued:
                    sess.seq_enqueued = seq
        else:
            rec = effect(idx)
            # effect already recorded (pre-ack); skip double record
        if "end" in rec:
            raise rehydrate_flow_exception(rec["end"])
        with flowprof_frame("serialize"):
            return deserialize(rec["payload"])

    def open_session(self, flow: FlowLogic, party: Party) -> FlowSession:
        def effect(idx):
            sid = _sid_for(self.flow_id, idx)
            sess = self.smm.register_session(sid, party, self)
            self.smm.send_to(
                party,
                SessionInit(sid, class_path(type(flow)), b"",
                            trace=self.trace_span.wire(),
                            deadline=self.deadline_t or 0.0),
                msg_id=f"{self.flow_id}:op{idx}",
                track_kind="init", track_sid=sid,
                deadline_s=self._retry_deadline_s(),
            )
            self.smm.wait_or_killed(
                lambda: sess.peer_sid is not None or sess.rejected is not None,
                executor=self, park_key=("sid", sid),
            )
            if sess.rejected is not None:
                raise FlowException(f"session rejected: {sess.rejected}")
            return {"sid": sid, "peer_sid": sess.peer_sid}

        def replay(idx, rec):
            sess = self.smm.register_session(rec["sid"], party, self)
            sess.peer_sid = rec["peer_sid"]

        rec = self._do_op(effect, replay)
        self.sessions.append(rec["sid"])
        return FlowSession(self, rec["sid"], party)

    def op_accept_session(self) -> FlowSession:
        """Responder op 0: bind the initiator's session."""

        def effect(idx):
            info = self.init_info
            sid = _sid_for(self.flow_id, idx)
            sess = self.smm.register_session(sid, info["peer"], self)
            sess.peer_sid = info["peer_sid"]
            self.smm.send_to(
                info["peer"],
                SessionConfirm(info["peer_sid"], sid),
                msg_id=f"{self.flow_id}:confirm",
            )
            return {"sid": sid, "peer_sid": info["peer_sid"],
                    "peer": info["peer"]}

        def replay(idx, rec):
            sess = self.smm.register_session(rec["sid"], rec["peer"], self)
            sess.peer_sid = rec["peer_sid"]
            self.smm.send_to(
                rec["peer"], SessionConfirm(rec["peer_sid"], rec["sid"]),
                msg_id=f"{self.flow_id}:confirm",
            )

        rec = self._do_op(effect, replay)
        self.sessions.append(rec["sid"])
        return FlowSession(self, rec["sid"], rec["peer"])

    def op_end_session(self, local_sid: int, error: str) -> None:
        def effect(idx):
            sess = self.smm.session(local_sid)
            seq = 0
            if sess.peer_sid is not None:
                sess.seq_out += 1
                seq = sess.seq_out
                self.smm.send_to(
                    sess.peer, SessionEnd(sess.peer_sid, error, seq),
                    msg_id=f"{self.flow_id}:op{idx}",
                    track_kind="data", track_sid=local_sid,
                    deadline_s=self._retry_deadline_s(),
                )
            return {"i": idx, "seq": seq}

        def replay(idx, rec):
            seq = rec.get("seq", 0)
            if seq:
                sess = self.smm.session(local_sid)
                if seq > sess.seq_out:
                    sess.seq_out = seq

        self._do_op(effect, replay)

    def op_wait_ledger_commit(self, tx_id):
        def effect(idx):
            stx = self.smm.wait_or_killed(
                lambda: self.smm.lookup_committed(tx_id),
                executor=self, park_key=("tx", tx_id),
            )
            return {"stx": stx}

        rec = self._do_op(effect)
        return rec["stx"]

    # ------------------------------------------------------------ lifecycle
    def run_once(self) -> str:
        """Execute on the calling worker thread until the flow finishes,
        parks, or dies → "finished" | "parked"."""
        if self.deadline_t is not None:
            # bind the propagated deadline for this execution segment so
            # every downstream submit on this thread (serving scheduler,
            # notary request, consensus client) sheds already-dead work
            with deadline_scope(self.deadline_t):
                return self._run_acct()
        return self._run_acct()

    def _run_acct(self) -> str:
        acct = self.prof_acct
        if acct is not None:
            fp = active_flowprof()
            if fp is not None:
                # activate the phase account for this execution segment:
                # frames/hints the flow body opens on this thread (and the
                # scheduler's submit-time capture) book to this flow
                with fp.activate(acct):
                    return self._run_traced()
        return self._run_traced()

    def _run_traced(self) -> str:
        span = self.trace_span
        if not span.sampled:
            return self._run_body()
        # activate for the duration of this execution segment: every span
        # the flow body opens on this thread (verify, scheduler submit,
        # notary attest) parents under the flow span via tracer.current()
        with tracer().activate(span):
            return self._run_body()

    def _run_body(self) -> str:
        try:
            if (self.deadline_t is not None
                    and time.time() >= self.deadline_t
                    and not self._pinned()):
                # the caller already gave up: fail here, before any
                # verify/notary work — goodput, not throughput. The
                # deadline itself (not the governor) is the opt-in, so a
                # propagated deadline sheds even with overload off; the
                # governor only adds counting + SLO observation.
                ov = active_overload()
                if ov is not None:
                    ov.note_deadline_shed(
                        str(getattr(self.flow, "priority", "service"))
                        if self.flow is not None else "service"
                    )
                raise FlowException("flow deadline exceeded")
            if self.responder_cls is not None:
                session = self.op_accept_session()
                self.flow = self.responder_cls(session)
            self.flow._executor = self
            self.flow.services = self.smm.services
            self.flow.our_identity = self.smm.our_identity
            result = self.flow.call()
            self._finish(result, None)
        except _FlowParked:
            return "parked"
        except FlowKilledException:
            if self.killed:
                # explicit kill: tell counterparties (SessionEnd), surface
                # a failed result, drop checkpoint + session state — all
                # via the normal finish path. An SMM *shutdown* instead
                # cancels quietly and preserves checkpoints for restore.
                self._finish(None, FlowException("flow was killed"))
            else:
                try:
                    self.result.cancel()
                except Exception:
                    pass
        except Exception as e:  # flow failure → future + peers
            self._finish(None, e)
        return "finished"

    def _finish(self, result, error):
        error_msg = "" if error is None else f"{type(error).__name__}: {error}"
        if error is not None and not isinstance(error, FlowException):
            # non-FlowException internals are not leaked to peers, matching
            # the reference's error propagation rules
            error_msg = "counterparty flow failed"
        for sid in self.sessions:
            try:
                sess = self.smm.session(sid)
                if sess.peer_sid is not None:
                    # sequenced AFTER every data this flow sent on the
                    # session: the peer defers the End until the data
                    # has arrived (retransmits fill any gap), so an End
                    # racing a delayed payload can no longer kill the
                    # peer's receive. Deterministic across crash-replay:
                    # seq_out is restored from the replayed send records.
                    self.smm.send_to(
                        sess.peer,
                        SessionEnd(sess.peer_sid, error_msg,
                                   sess.seq_out + 1),
                        msg_id=f"{self.flow_id}:end{sid}",
                        track_kind="data", track_sid=sid,
                        deadline_s=self._retry_deadline_s(),
                    )
            except Exception:
                pass
        # engine-managed soft-lock release (reference: VaultSoftLockManager
        # hooks flow completion). Flows must NOT release in their own
        # try/finally: a park unwinds the Python stack through finally
        # blocks, so a flow-managed release would free its selected states
        # mid-suspension — a rival spends them, and the replayed flow
        # double-spends at the notary.
        try:
            vault = getattr(self.smm.services, "vault_service", None)
            if vault is not None:
                vault.soft_lock_release(self.flow_id)
        except Exception:
            pass
        self.smm.flow_finished(self)
        try:
            if error is None:
                self.result.set_result(result)
            else:
                self.result.set_exception(error)
        except Exception:
            pass  # future already cancelled (shutdown race)


class StateMachineManager:
    """Owns all running flows of one node; dispatches session messages;
    restores persisted flows at startup (reference:
    StateMachineManager.kt:238-265 restoreFibersFromCheckpoints)."""

    def __init__(
        self,
        messaging,
        checkpoints: CheckpointStorage,
        our_identity: Party,
        party_resolver=None,
        services=None,
        max_workers: int = 16,
        parking_grace_s: float = 0.05,
        retry_policy: "RetryPolicy | None" = _DEFAULT_RETRY_POLICY,
    ):
        self.messaging = messaging
        self.checkpoints = checkpoints
        self.our_identity = our_identity
        # per-session retransmission of unacked Init/Data/End messages
        # (exponential backoff, jitter, hard deadline — see _Retrans).
        # The default policy keeps first retransmits past the grace of an
        # in-order transport; chaos tests tighten it. Pass None to disable
        # retransmission (a transport with its own delivery guarantees).
        self._retry_policy = retry_policy
        self._retx_rng = random.Random(f"retx:{our_identity.name}")
        self._unacked: dict[str, _Retrans] = {}
        self._retx_timer: threading.Thread | None = None
        # sids of FINISHED flows (bounded FIFO): distinguishes an End for
        # a completed-and-pruned session (safe to ack away) from one for
        # a session a crash-replayed flow has not re-registered YET
        # (must stay unacked so the broker redelivers it post-replay)
        self._finished_sids: set[int] = set()
        self._finished_sids_order: deque[int] = deque(maxlen=4096)
        self.services = services
        if services is not None and hasattr(services, "add_commit_listener"):
            # a PARKED wait_for_ledger_commit only resumes via its wake
            # key; recording must push the wake (polling covers only the
            # pre-park grace window — without this hook, any flow that
            # parked waiting on a commit slept forever)
            services.add_commit_listener(self.notify_ledger_commit)
        self._party_resolver = party_resolver or (lambda name: None)
        # with flowprof on at construction, the SMM monitor sits over a
        # timed-acquire RLock so blocked acquisition books to lock_wait
        # (enabling flowprof later leaves an existing SMM untimed — the
        # hook costs a lock-construction decision, never a per-acquire
        # check while off); with contention timing also on, the
        # contention wrapper sits over THAT, so the hottest monitor in
        # the process is always in the top-contended table under its
        # stable "engine.smm" site name, whatever order install() ran in
        from corda_tpu.observability.contention import (
            active_contention,
            timed_lock,
            wrap_lock,
        )

        _fp = active_flowprof()
        _smm_inner = _fp.timed_rlock() if _fp is not None else None
        if active_contention() is not None:
            if _smm_inner is None:
                _smm_inner = timed_lock("engine.smm", reentrant=True)
            else:
                _smm_inner = wrap_lock(_smm_inner, "engine.smm")
        self._lock = threading.Condition(_smm_inner)
        self._sessions: dict[int, _SessionState] = {}
        self._flows: dict[str, _FlowExecutor] = {}
        self._consumed_msg_ids: set[str] = set()
        self._committed = {}  # tx_id -> SignedTransaction (ledger hook)
        self._closed = False
        # ----- scheduler state (bounded pool + parked flows)
        self._max_workers = max_workers
        self._parking_grace_s = parking_grace_s
        self._runq: deque[str] = deque()
        self._queued: set[str] = set()
        self._running: set[str] = set()
        self._parked: dict = {}               # wake key -> set[flow_id]
        self._park_key_of: dict[str, object] = {}
        self._rewake: set[str] = set()        # woken while still running
        self._sleepers: dict[str, float] = {} # flow_id -> deadline
        self._results: dict[str, Future] = {} # persistent per-flow futures
        # flow id -> open trace span (sampled flows only): outlives the
        # executor across park/replay like the result future does; finished
        # (and pruned) in flow_finished / _fail_unrunnable
        self._flow_spans: dict[str, object] = {}
        self._killed_ids: set[str] = set()
        # flows past their point of no return (FlowLogic.commit_pin) —
        # exempt from deadline sheds; survives park/replay in memory and
        # crash restore via the oplog marker (pruned with the flow)
        self._commit_pinned: set[str] = set()
        self._workers: list[threading.Thread] = []
        self._timer: threading.Thread | None = None
        messaging.add_handler(SESSION_TOPIC, self._on_message)

    # ------------------------------------------------------------ tracing
    def span_of(self, flow_id: str):
        """The flow's open trace span, or the shared no-op."""
        with self._lock:
            return self._flow_spans.get(flow_id, NOOP_SPAN)

    def _open_flow_span(self, flow_id: str, flow_cls: str, *,
                        responder: bool = False,
                        parent_wire: str = "") -> None:
        """Root (initiator) or wire-parented (responder) flow span; only
        sampled spans enter the table — unsampled flows cost one lookup
        miss. A responder NEVER roots its own trace: the sampling
        decision is the initiator's, carried (or withheld) on the wire —
        an empty parent context means "not sampled", not "re-roll"
        (re-rolling would leak orphan fragment traces at the configured
        rate per responder and overshoot the sampling knob)."""
        trc = tracer()
        if responder:
            span = trc.start(
                SPAN_FLOW_RESPONDER, TraceContext.from_wire(parent_wire),
                attrs={"flow.id": flow_id, "flow.class": flow_cls,
                       "node": str(self.our_identity.name)},
            )
        else:
            span = trc.root(
                SPAN_FLOW,
                attrs={"flow.id": flow_id, "flow.class": flow_cls,
                       "node": str(self.our_identity.name)},
            )
        if span.sampled:
            with self._lock:
                self._flow_spans[flow_id] = span

    def _close_flow_span(self, flow_id: str, error=None) -> None:
        with self._lock:
            span = self._flow_spans.pop(flow_id, None)
        if span is not None:
            if error is not None:
                span.set_error(error)
            span.finish()

    # ------------------------------------------------------------ public
    def start_flow(self, flow: FlowLogic, flow_id: str | None = None,
                   deadline_s: float | None = None) -> FlowHandle:
        # adaptive admission (docs/OVERLOAD.md) gates FIRST: a rejection
        # must cost the caller one exception — no span, no flowprof
        # account, and above all no checkpoint write
        priority = str(getattr(flow, "priority", "service"))
        ov = active_overload()
        if ov is not None:
            if not ov.try_admit(priority):
                raise FlowAdmissionError(
                    f"flow admission rejected ({priority}): node over "
                    "concurrency limit"
                )
        deadline_t = time.time() + deadline_s if deadline_s is not None else None
        flow_id = flow_id or secrets.token_hex(16)
        self._open_flow_span(flow_id, class_path(type(flow)))
        fp = active_flowprof()
        if fp is not None:
            fp.open(flow_id, class_path(type(flow)))
        blob = serialize({
            "cls": class_path(type(flow)),
            "fields": flow.flow_fields(),
            "responder": False,
            # omitted when unset: checkpoints of deadline-less flows (and
            # all pre-overload checkpoints) keep their exact byte shape
            **({"deadline": deadline_t} if deadline_t else {}),
        })
        self.checkpoints.add_flow(flow_id, blob, str(self.our_identity.name),
                                  time.time())
        fut: Future = Future()
        if ov is not None:
            t0 = time.monotonic()

            def _release(f, _ov=ov, _p=priority, _t0=t0):
                try:
                    err = f.exception() is not None
                except Exception:
                    err = True  # cancelled future (shutdown)
                _ov.release(_p, time.monotonic() - _t0, error=err)

            # the future outlives this executor across park/replay, so
            # one done-callback frees the admission slot exactly once
            # however many executors the flow burns through
            fut.add_done_callback(_release)
        ex = _FlowExecutor(self, flow_id, [], flow, result=fut)
        ex.deadline_t = deadline_t
        with self._lock:
            self._flows[flow_id] = ex
            self._results[flow_id] = fut
        self._enqueue(flow_id)
        return FlowHandle(flow_id, fut)

    def restore(self) -> list[FlowHandle]:
        """Re-spawn every checkpointed flow; replay brings each to its live
        point."""
        handles = []
        for flow_id, blob, _our, _ts in self.checkpoints.all_flows():
            with self._lock:
                if flow_id in self._flows:
                    continue
            ex = self._rebuild(flow_id, blob)
            if ex is None:
                continue
            self._enqueue(flow_id)
            handles.append(FlowHandle(flow_id, ex.result))
        return handles

    def _rebuild(self, flow_id: str, blob: bytes) -> "_FlowExecutor | None":
        """Reconstruct an executor from its checkpoint (both the restart
        restore path and the park/resume path)."""
        meta = deserialize(blob)
        oplog = self.checkpoints.load_oplog(flow_id)
        # reconstruct consumed-message dedupe set from receive records —
        # under the lock: _rebuild also runs on the park/resume path while
        # worker threads consume ids concurrently (consume_inbound)
        with self._lock:
            for rec in oplog:
                if isinstance(rec, dict) and "msg_id" in rec:
                    self._consumed_msg_ids.add(rec["msg_id"])
                # re-establish the point-of-no-return pin BEFORE any
                # resume-time deadline decision (crash restore loses the
                # in-memory set; the shed check runs ahead of replay)
                if isinstance(rec, dict) and rec.get("commit_pin"):
                    self._commit_pinned.add(flow_id)
        cls = load_class(meta["cls"])
        with self._lock:
            fut = self._results.setdefault(flow_id, Future())
        if meta["responder"]:
            ex = _FlowExecutor(self, flow_id, oplog, None,
                               responder_cls=cls, result=fut)
        else:
            flow = cls.from_flow_fields(meta["fields"])
            ex = _FlowExecutor(self, flow_id, oplog, flow, result=fut)
        # .get: pre-overload checkpoints carry no deadline and decode fine
        ex.deadline_t = meta.get("deadline")
        with self._lock:
            ex.killed = flow_id in self._killed_ids
            self._flows[flow_id] = ex
        return ex

    # ------------------------------------------------------- scheduler
    def _enqueue(self, flow_id: str) -> None:
        with self._lock:
            if self._closed or flow_id in self._queued:
                return
            self._queued.add(flow_id)
            self._runq.append(flow_id)
            self._spawn_workers_locked()
            self._lock.notify_all()

    def _spawn_workers_locked(self) -> None:
        live = [t for t in self._workers if t.is_alive()]
        self._workers = live
        want = min(self._max_workers, len(self._runq) + len(self._running))
        for i in range(len(live), want):
            t = threading.Thread(
                target=self._worker_loop, daemon=True,
                name=f"flow-worker-{i}",
            )
            self._workers.append(t)
            t.start()

    def _worker_loop(self) -> None:
        while True:
            with self._lock:
                while not self._runq and not self._closed:
                    timeout = self._wake_due_sleepers_locked()
                    self._lock.wait(timeout=timeout)
                if self._closed and not self._runq:
                    return
                flow_id = self._runq.popleft()
                self._queued.discard(flow_id)
                if flow_id in self._running:
                    # executing elsewhere: flag so the running worker
                    # re-queues on exit (the wake that queued this pop must
                    # not be lost if that run parks after our check)
                    self._rewake.add(flow_id)
                    continue
                self._running.add(flow_id)
                ex = self._flows.get(flow_id)
            try:
                if ex is None:
                    blob = self.checkpoints.get_flow(flow_id)
                    if blob is None:
                        continue  # finished while queued
                    try:
                        ex = self._rebuild(flow_id, blob)
                    except Exception as e:
                        # an unreconstructible flow must FAIL loudly, not
                        # vanish: resolve its future and drop the state
                        self._fail_unrunnable(flow_id, e)
                        continue
                    if ex is None:
                        continue
                ex.run_once()
            except Exception:
                pass  # executor-level failures resolve the flow future
            finally:
                with self._lock:
                    self._running.discard(flow_id)
                    # parked-with-pending-wake race: a wake fired while we
                    # were marked running; it couldn't re-queue then, so
                    # honour it now (only if the flow actually parked —
                    # a finished flow has no park state left)
                    if flow_id in self._rewake:
                        self._rewake.discard(flow_id)
                        if flow_id in self._park_key_of:
                            self._unpark_locked(flow_id)

    def _fail_unrunnable(self, flow_id: str, error: Exception) -> None:
        self._close_flow_span(flow_id, error=error)
        fp = active_flowprof()
        if fp is not None:
            fp.close(flow_id)
        with self._lock:
            fut = self._results.pop(flow_id, None)
            self._flows.pop(flow_id, None)
            self._park_key_of.pop(flow_id, None)
            self._sleepers.pop(flow_id, None)
            self._killed_ids.discard(flow_id)
            self._commit_pinned.discard(flow_id)
        if fut is not None and not fut.done():
            try:
                fut.set_exception(
                    FlowException(f"flow cannot be rebuilt for resume: {error}")
                )
            except Exception:
                pass

    def _wake_due_sleepers_locked(self) -> float:
        """Move sleepers past their deadline onto the run queue; return the
        wait timeout until the next deadline (capped)."""
        now = time.time()
        due = [f for f, dl in self._sleepers.items() if dl <= now]
        for f in due:
            self._sleepers.pop(f, None)
            self._unpark_locked(f)
        nxt = min(self._sleepers.values()) - now if self._sleepers else 0.5
        return max(0.01, min(nxt, 0.5))

    def _start_timer_locked(self) -> None:
        """Dedicated sleeper timer: due deadlines must fire even when every
        worker is busy (the idle-loop check alone starves under sustained
        load)."""
        if self._timer is not None and self._timer.is_alive():
            return

        def loop():
            while True:
                with self._lock:
                    if self._closed:
                        return
                    if not self._sleepers:
                        self._timer = None
                        return
                    timeout = self._wake_due_sleepers_locked()
                time.sleep(min(timeout, 0.05))

        self._timer = threading.Thread(
            target=loop, daemon=True, name="flow-sleep-timer"
        )
        self._timer.start()

    def _park_locked(self, flow_id: str, key, deadline: float | None) -> None:
        """Caller holds the lock and has just re-checked the condition."""
        self._park_key_of[flow_id] = key
        if key is not None:
            self._parked.setdefault(key, set()).add(flow_id)
        if deadline is not None:
            self._sleepers[flow_id] = deadline
            self._start_timer_locked()
        # drop the executor: the flow's state IS its checkpoint now; the
        # resume path rebuilds and replays (sessions stay registered and
        # keep buffering inbound messages while parked)
        self._flows.pop(flow_id, None)

    def _unpark_locked(self, flow_id: str) -> None:
        if flow_id in self._running:
            # raced with the parking worker: flag for re-queue on its exit
            self._rewake.add(flow_id)
            return
        key = self._park_key_of.pop(flow_id, "absent")
        if key == "absent":
            return
        fp = active_flowprof()
        if fp is not None:
            # close the hinted-wait window (opened at wait_or_killed
            # entry): the park wall books to the hinted phase
            fp.note_unpark(fp.acct_of(flow_id))
        if key is not None:
            group = self._parked.get(key)
            if group is not None:
                group.discard(flow_id)
                if not group:
                    self._parked.pop(key, None)
        self._sleepers.pop(flow_id, None)
        if not self._closed and flow_id not in self._queued:
            self._queued.add(flow_id)
            self._runq.append(flow_id)
        self._lock.notify_all()

    def _wake_key_locked(self, key) -> None:
        for flow_id in list(self._parked.get(key, ())):
            self._unpark_locked(flow_id)

    def flows_in_progress(self) -> list[str]:
        with self._lock:
            live = set(self._flows) | set(self._park_key_of) | self._queued
            return list(live)

    def flows_detail(self) -> dict[str, str]:
        """flow id → what it is doing ("running", "queued", or
        "parked@<wake key>") — the operator's first question about a
        wedged flow is what it is waiting on. Kept separate from
        ``flows_in_progress`` so id-membership consumers stay stable."""
        with self._lock:
            out: dict[str, str] = {}
            for fid in set(self._flows) | set(self._park_key_of) | self._queued:
                if fid in self._park_key_of:
                    out[fid] = f"parked@{self._park_key_of[fid]}"
                elif fid in self._queued:
                    out[fid] = "queued"
                else:
                    out[fid] = "running"
            return out

    def handle_of(self, flow_id: str) -> FlowHandle | None:
        """Handle for a running flow (None once finished and pruned)."""
        with self._lock:
            fut = self._results.get(flow_id)
        return FlowHandle(flow_id, fut) if fut is not None else None

    def kill_flow(self, flow_id: str) -> bool:
        """Terminate one running flow (reference: CordaRPCOps.killFlow).
        The flow's next suspension point raises; its checkpoint is
        removed. A parked flow is woken so it can observe the kill."""
        with self._lock:
            known = (
                flow_id in self._flows
                or flow_id in self._park_key_of
                or flow_id in self._queued
            )
            if not known:
                return False
            self._killed_ids.add(flow_id)
            ex = self._flows.get(flow_id)
            if ex is not None:
                ex.killed = True
            self._unpark_locked(flow_id)
            self._lock.notify_all()
        return True

    def consume_inbound(self, sess: _SessionState):
        """Pop the head of a session's inbound queue AND mark its logical
        id consumed in one locked step (see op_receive for the retransmit
        race this closes). The id is marked in-memory only — durability
        still rides the op-log record; on a crash before the record, the
        set is gone with the process and the peer's retransmit re-offers
        the message to the replayed flow."""
        with self._lock:
            item = sess.inbound.popleft()
            if item[2]:
                self._consumed_msg_ids.add(item[2])
            return item

    def notify_ledger_commit(self, stx) -> None:
        with self._lock:
            if self.services is None:
                # no storage backing lookup_committed: keep the in-memory
                # feed. With services, storing here would duplicate the
                # whole validated-transactions store for the node's
                # lifetime — the wake alone suffices.
                self._committed[stx.id] = stx
            self._wake_key_locked(("tx", stx.id))
            self._lock.notify_all()

    def lookup_committed(self, tx_id):
        # storage-backed lookup first (survives restarts), then the
        # in-memory feed
        if self.services is not None:
            stored = self.services.validated_transactions.get(tx_id)
            if stored is not None:
                return stored
        return self._committed.get(tx_id)

    def stop(self) -> None:
        with self._lock:
            self._closed = True
            self._runq.clear()
            self._queued.clear()
            self._lock.notify_all()
        self.messaging.stop()

    # ------------------------------------------------------------ internals
    def session(self, sid: int) -> _SessionState:
        with self._lock:
            sess = self._sessions.get(sid)
        if sess is None:
            raise FlowException(f"unknown session {sid}")
        return sess

    def register_session(self, sid: int, peer: Party, executor) -> _SessionState:
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                sess = _SessionState(sid, peer, executor)
                self._sessions[sid] = sess
            else:
                # a resumed (parked or restored) flow re-registers its own
                # sid on a FRESH executor: rebind but keep the buffered
                # inbound and the confirmed peer_sid — messages that
                # arrived while parked must not be lost
                sess.executor = executor
            return sess

    def send_to(self, party: Party, obj, *, msg_id: str,
                track_kind: str | None = None, track_sid: int = 0,
                deadline_s: float | None = None) -> None:
        with flowprof_frame("serialize"):
            payload = serialize(obj)
        if track_kind == "data":
            # transit accounting (flowprof): stamp Data/End sends by their
            # LOGICAL id — _buffer on the receiving SMM books send→delivery
            # as message_transit for the consuming flow. Retransmits reuse
            # the first send's stamp, so transit honestly includes the
            # loss-recovery wall.
            fp = active_flowprof()
            if fp is not None:
                fp.note_sent(_logical_id(msg_id))
        if track_kind is not None:
            # hop evidence (cluster observatory): wall-clock send stamp on
            # THIS node, joined by the receiving engine into a per-hop
            # net.transit span. Same first-stamp-wins semantics as above.
            cl = active_cluster()
            if cl is not None:
                cl.note_send(
                    str(self.our_identity.name), str(party.name),
                    track_kind, _logical_id(msg_id),
                    current_trace_id() or "",
                )
        # register BEFORE transmitting: a fast peer's reply (Confirm/Ack)
        # can be processed in the window after send — it must find the
        # entry to settle, not race past an empty map and leave a zombie
        # retransmitting to its deadline
        if track_kind is not None and self._retry_policy is not None:
            self._track_unacked(str(party.name), payload, msg_id,
                                track_kind, track_sid, deadline_s)
        self.messaging.send(str(party.name), SESSION_TOPIC, payload,
                            msg_id=msg_id)

    # ----------------------------------------------- session retransmission
    def _track_unacked(self, party_name: str, payload: bytes, base_id: str,
                       kind: str, sid: int, deadline_s: float | None) -> None:
        policy = self._retry_policy
        ov = active_overload()
        if ov is not None:
            # a FRESH tracked send earns retry-budget tokens for this
            # peer edge (outside the SMM lock — the governor locks itself)
            ov.note_send("session", party_name)
        entry = _Retrans(
            base_id, party_name, payload, kind, sid, policy, self._retx_rng,
            deadline_s if deadline_s is not None else policy.deadline_s,
        )
        with self._lock:
            if self._closed or base_id in self._unacked:
                return
            self._unacked[base_id] = entry
            self._start_retx_timer_locked()

    def _start_retx_timer_locked(self) -> None:
        if self._retx_timer is not None and self._retx_timer.is_alive():
            return

        def loop():
            while True:
                # governor prep OUTSIDE the SMM lock: sync_net_events
                # walks the netstats event ring under netstats' own lock
                ov = active_overload()
                if ov is not None:
                    ov.sync_net_events()
                our = str(self.our_identity.name)
                with self._lock:
                    if self._closed or not self._unacked:
                        self._retx_timer = None
                        return
                    now = time.monotonic()
                    resend: list[tuple[str, bytes, str]] = []
                    for e in list(self._unacked.values()):
                        if now >= e.deadline:
                            # budget exhausted: the SENDING flow learns —
                            # a session that cannot deliver is failed
                            # locally rather than hanging forever
                            self._unacked.pop(e.base_id, None)
                            self._fail_session_locked(
                                e.sid, e.kind,
                                "session retry deadline exceeded "
                                f"(peer {e.party_name} unreachable)",
                            )
                            continue
                        if e.next_at <= now:
                            if ov is not None and not ov.allow_retry(
                                    "session", e.party_name):
                                # retry budget exhausted for this edge:
                                # hold one backoff without sending — the
                                # entry's hard deadline still bounds the
                                # total wait, and fresh sends refill
                                e.next_at = now + e.backoff_s
                                continue
                            e.attempt += 1
                            backoff = self._retry_policy.backoff_s(
                                e.attempt, self._retx_rng
                            )
                            if ov is not None and ov.edge_suspected(
                                    our, e.party_name):
                                # partition suspected on this edge (PR
                                # 15's net.partition_suspect): widen
                                # pre-emptively so the heal meets a
                                # drained backoff, not a storm
                                backoff *= ov.suspect_backoff_scale
                            e.backoff_s = backoff
                            if e.attempt >= 2:
                                # FULL jitter over the whole backoff, not
                                # the policy's ±fraction: after a long
                                # outage every parked entry reaches
                                # next_at in the same tick, and fractional
                                # jitter re-releases them as one N-wide
                                # burst. Attempt 1 keeps the policy
                                # cadence (first-retransmit latency).
                                e.next_at = now + self._retx_rng.uniform(
                                    0.0, backoff
                                )
                            else:
                                e.next_at = now + backoff
                            resend.append((
                                e.party_name, e.payload,
                                f"{e.base_id}~{e.attempt}",
                            ))
                for name, payload, wire_id in resend:
                    try:
                        self.messaging.send(
                            name, SESSION_TOPIC, payload, msg_id=wire_id
                        )
                    except Exception:
                        pass  # transport down: the next tick retries
                # sleep until the soonest retransmit/deadline instead of a
                # fixed high-rate poll — an idle buffer with a 2s backoff
                # must not contend the SMM lock 50 times a second. The
                # condition wakes early on any SMM notify (new entries
                # notify via _track_unacked's lock exit), and the wait
                # re-evaluates from scratch either way.
                with self._lock:
                    if self._closed:
                        self._retx_timer = None
                        return
                    now = time.monotonic()
                    nxt = min(
                        (min(e.next_at, e.deadline)
                         for e in self._unacked.values()),
                        default=now + 0.5,
                    )
                    self._lock.wait(timeout=max(0.005, min(nxt - now, 0.5)))

        self._retx_timer = threading.Thread(
            target=loop, daemon=True, name="flow-session-retx"
        )
        self._retx_timer.start()

    def _fail_session_locked(self, sid: int, kind: str, error: str) -> None:
        sess = self._sessions.get(sid)
        if sess is None:
            return  # flow already finished; nothing is waiting
        if kind == "init":
            sess.rejected = error   # open_session waits on rejected/confirm
        else:
            sess.inbound.append(("end", error, "", None, 0))
        self._wake_key_locked(("sid", sid))
        self._lock.notify_all()

    def ack_session_msg(self, party: Party, logical_id: str) -> None:
        """Receiver side: acknowledge a consumed Data/End message (fresh
        wire id per ack so transport dedupe never swallows a re-ack)."""
        try:
            self.messaging.send(
                str(party.name), SESSION_TOPIC,
                serialize(SessionAck(logical_id)),
            )
        except Exception:
            pass  # sender will retransmit; we re-ack the duplicate

    def _drop_unacked_for_sid(self, sid: int, kind: str | None = None) -> None:
        """Confirm/Reject arrival settles the Init retransmit for a sid."""
        with self._lock:
            for bid in [
                b for b, e in self._unacked.items()
                if e.sid == sid and (kind is None or e.kind == kind)
            ]:
                self._unacked.pop(bid, None)

    def wait_or_killed(self, predicate, timeout: float | None = None,
                       executor=None, park_key=None, sleep_deadline=None):
        """Block until predicate() returns non-None/True; FlowKilled on
        shutdown or when this flow was explicitly killed. Runs under the
        SMM lock.

        With a ``park_key`` (or ``sleep_deadline``), a wait that outlives
        the parking grace PARKS the flow instead of holding its worker
        thread: the flow's state collapses to its checkpoint, the key is
        registered, and ``_FlowParked`` unwinds the worker. The wake
        (message arrival / commit / deadline) re-queues the flow, which
        replays to this exact wait and re-checks."""
        parkable = executor is not None and (
            park_key is not None or sleep_deadline is not None
        )
        deadline = None if timeout is None else time.monotonic() + timeout
        grace = (
            time.monotonic() + self._parking_grace_s if parkable else None
        )
        # hinted-wait window (flowprof): with a park hint set on the
        # calling flow (the notary client's notary_rtt scope), the wall
        # from here to satisfaction — whether the wait stays on-thread or
        # parks — books to the hinted phase; cross-thread attributions
        # landing inside the window (the response's message_transit) are
        # subtracted by note_unpark so the window is never double-booked.
        # The park path leaves the window OPEN: _unpark_locked closes it.
        fp = active_flowprof()
        acct = fp.current() if fp is not None else None
        if acct is not None:
            fp.note_park(acct)
        with self._lock:
            while True:
                if self._closed or (executor is not None and executor.killed):
                    raise FlowKilledException()
                val = predicate()
                if val not in (None, False):
                    if acct is not None:
                        fp.note_unpark(acct)
                    return val
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    if acct is not None:
                        fp.note_unpark(acct)
                    return None
                if grace is not None and now >= grace:
                    self._park_locked(
                        executor.flow_id, park_key, sleep_deadline
                    )
                    raise _FlowParked()
                waits = [0.5]
                if deadline is not None:
                    waits.append(deadline - now)
                if grace is not None:
                    waits.append(grace - now)
                self._lock.wait(timeout=max(0.001, min(waits)))

    def flow_finished(self, ex: _FlowExecutor) -> None:
        self._close_flow_span(ex.flow_id)
        fp = active_flowprof()
        if fp is not None:
            fp.close(ex.flow_id)
        self.checkpoints.remove_flow(ex.flow_id)
        with self._lock:
            self._flows.pop(ex.flow_id, None)
            self._results.pop(ex.flow_id, None)
            self._killed_ids.discard(ex.flow_id)
            self._commit_pinned.discard(ex.flow_id)
            self._park_key_of.pop(ex.flow_id, None)
            self._sleepers.pop(ex.flow_id, None)
            for sid in ex.sessions:
                self._sessions.pop(sid, None)
                if sid not in self._finished_sids:
                    if (len(self._finished_sids_order)
                            == self._finished_sids_order.maxlen):
                        self._finished_sids.discard(
                            self._finished_sids_order[0]
                        )
                    self._finished_sids_order.append(sid)
                    self._finished_sids.add(sid)

    # ------------------------------------------------------------ dispatch
    def _on_message(self, msg, ack=None) -> None:
        logical = _logical_id(msg.msg_id)
        obj = deserialize(msg.payload)
        if isinstance(obj, SessionAck):
            with self._lock:
                self._unacked.pop(obj.msg_id, None)
            if ack:
                ack()
            return
        with self._lock:
            consumed = logical in self._consumed_msg_ids
        if consumed:
            # duplicate of an already-consumed message (retransmit whose
            # ack was lost, or broker redelivery): re-ack so the sender's
            # retransmit buffer settles, then drop
            if isinstance(obj, (SessionData, SessionEnd)):
                peer = self._party_resolver(msg.sender)
                if peer is not None:
                    self.ack_session_msg(peer, logical)
            if ack:
                ack()
            return
        if isinstance(obj, SessionInit):
            self._handle_init(msg, obj, ack)
        elif isinstance(obj, SessionConfirm):
            with self._lock:
                sess = self._sessions.get(obj.initiator_session_id)
                if sess is not None:
                    sess.peer_sid = obj.responder_session_id
                    self._wake_key_locked(("sid", obj.initiator_session_id))
                    self._lock.notify_all()
            self._drop_unacked_for_sid(obj.initiator_session_id, "init")
            if ack:
                ack()
        elif isinstance(obj, SessionReject):
            with self._lock:
                sess = self._sessions.get(obj.initiator_session_id)
                if sess is not None:
                    sess.rejected = obj.error
                    self._wake_key_locked(("sid", obj.initiator_session_id))
                    self._lock.notify_all()
            self._drop_unacked_for_sid(obj.initiator_session_id, "init")
            if ack:
                ack()
        elif isinstance(obj, SessionData):
            self._buffer(obj.recipient_session_id, "data", obj.payload,
                         logical, ack, msg.sender, obj.seq)
        elif isinstance(obj, SessionEnd):
            self._buffer(obj.recipient_session_id, "end", obj.error,
                         logical, ack, msg.sender, obj.seq)

    def _buffer(self, sid: int, kind: str, body, msg_id: str, ack,
                sender: str = "", seq: int = 0) -> None:
        ack_peer = None
        transport_ack = False
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                # session may not be re-registered yet during replay: park
                # by leaving the transport unacked (broker redelivers) or
                # rely on the peer's session-level retransmit on mock. An
                # END to a session a FINISHED flow pruned instead settles
                # BOTH acks — an unacked End would otherwise retransmit to
                # its full deadline (and redeliver every broker visibility
                # window) after every completed flow. The finished-sids
                # check is what distinguishes that case from the replay
                # window, where acking away the End would strand the
                # replayed flow's receive.
                if (kind == "end" and msg_id and sender
                        and sid in self._finished_sids):
                    ack_peer = self._party_resolver(sender)
                    transport_ack = True
            elif msg_id and msg_id in self._consumed_msg_ids:
                # RE-CHECK consumed under the append lock: the dispatch-
                # entry check ran before this message waited on the lock,
                # and the original may have been consumed in between — a
                # stale append here would be replayed as a LATER message
                ack_peer = sess.peer
                transport_ack = True
                sess = None  # handled: fall through to the ack block
            elif any(q[2] == msg_id for q in sess.inbound if q[2]) or any(
                    q[2] == msg_id
                    for q in sess.seq_pending.values() if q[2]):
                # retransmit already buffered but not yet consumed: settle
                # this duplicate's transport lease (the buffered original's
                # own ack + session retransmit carry the delivery guarantee)
                transport_ack = True
                sess = None
            elif seq and seq <= sess.seq_enqueued:
                # a sequence position already delivered under another wire
                # id: nothing left to deliver, settle the transport lease
                # (the sender's own retransmit/deadline settles its entry)
                transport_ack = True
                sess = None
            else:
                if msg_id:
                    # transit telemetry at ARRIVAL — a message parked in
                    # seq_pending has finished its network leg even though
                    # delivery to the flow waits for the gap to fill
                    fp = active_flowprof()
                    if fp is not None:
                        ex = sess.executor
                        fp.take_transit(
                            msg_id,
                            fp.acct_of(ex.flow_id) if ex is not None
                            else None,
                        )
                    cl = active_cluster()
                    if cl is not None and sender:
                        ex = sess.executor
                        span = (self._flow_spans.get(ex.flow_id)
                                if ex is not None else None)
                        cl.note_recv(
                            str(self.our_identity.name), sender, msg_id,
                            span.trace_id if span is not None else "",
                        )
                entry = (kind, body, msg_id, ack, seq)
                if seq and seq > sess.seq_enqueued + 1:
                    # out of order: a lower-seq message is still in flight
                    # (dropped → retransmitting, or delayed). Park until
                    # the gap fills — delivering now would let this
                    # message (or the End) overtake the one the flow's
                    # next receive actually needs.
                    sess.seq_pending[seq] = entry
                    if sess.gap_since is None:
                        sess.gap_since = time.monotonic()
                    if not sess.gap_timer_armed:
                        # liveness backstop: if the gap never fills (the
                        # sender hit its retry deadline and gave up), a
                        # timer force-drains rather than park the
                        # receiving flow forever. Transient thread, only
                        # when reordering actually occurred — clean runs
                        # create no threads (the off-by-default pin).
                        sess.gap_timer_armed = True
                        t = threading.Timer(
                            self._gap_limit_s(), self._gap_check,
                            args=(sid,))
                        t.daemon = True
                        t.name = f"flow-session-gap-{sid}"
                        t.start()
                else:
                    sess.inbound.append(entry)
                    if seq:
                        sess.seq_enqueued = seq
                    # drain consecutive parked successors
                    nxt = sess.seq_enqueued + 1
                    while nxt in sess.seq_pending:
                        sess.inbound.append(sess.seq_pending.pop(nxt))
                        sess.seq_enqueued = nxt
                        nxt += 1
                    # the front gap just moved: clear the backstop clock,
                    # or restart it for the next gap in line
                    sess.gap_since = (None if not sess.seq_pending
                                      else time.monotonic())
                self._wake_key_locked(("sid", sid))
                self._lock.notify_all()
                return
        if ack_peer is not None and msg_id:
            self.ack_session_msg(ack_peer, msg_id)
        if transport_ack and ack:
            ack()

    def _gap_limit_s(self) -> float:
        """How long a sequence gap may park deliveries before the
        backstop concludes the missing message is never coming: the
        session retry deadline — past it the sender has failed the
        session on its side, so no retransmit can still be in flight."""
        if self._retry_policy is not None:
            return self._retry_policy.deadline_s
        return 60.0

    def _gap_check(self, sid: int) -> None:
        """Timer body for the sequencing liveness backstop (armed in
        _buffer when a message parks behind a gap). If the front gap is
        older than _gap_limit_s, force-drain seq_pending in sequence
        order: the flow then observes the loss as a protocol error /
        peer-end instead of parking forever — exactly the
        pre-sequencing failure mode, minus the reorder window. A late
        retransmit of the gap seq then lands `seq <= seq_enqueued` and
        is settled as a stale position."""
        rearm: float | None = None
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None or self._closed:
                return
            if not sess.seq_pending:
                sess.gap_timer_armed = False
                sess.gap_since = None
                return
            now = time.monotonic()
            started = sess.gap_since if sess.gap_since is not None else now
            if now - started >= self._gap_limit_s() - 0.05:
                for s in sorted(sess.seq_pending):
                    sess.inbound.append(sess.seq_pending.pop(s))
                    sess.seq_enqueued = max(sess.seq_enqueued, s)
                sess.gap_since = None
                sess.gap_timer_armed = False
                self._wake_key_locked(("sid", sid))
                self._lock.notify_all()
            else:
                # gap moved (partial drain) since the timer was armed:
                # check again when the current front gap would expire
                rearm = max(0.1, self._gap_limit_s() - (now - started))
        if rearm is not None:
            t = threading.Timer(rearm, self._gap_check, args=(sid,))
            t.daemon = True
            t.name = f"flow-session-gap-{sid}"
            t.start()

    def _handle_init(self, msg, init: SessionInit, ack) -> None:
        logical = _logical_id(msg.msg_id)
        flow_id = f"resp-{logical}"
        if not self.checkpoints.mark_init_processed(logical, flow_id):
            # duplicate Init (crash-replayed or retransmitted by the
            # initiator): our Confirm may have been lost — re-send it
            # (dedupe makes it harmless). Session ids derive determini-
            # stically from (flow id, op 0), so the Confirm can be
            # reconstructed even after the responder finished and its
            # session state was pruned.
            with self._lock:
                ex = self._flows.get(flow_id)
                resend = None
                if ex is not None:
                    for sid in ex.sessions:
                        sess = self._sessions.get(sid)
                        if sess is not None and sess.peer_sid == init.initiator_session_id:
                            resend = (sess.peer, SessionConfirm(sess.peer_sid, sid),
                                      f"{flow_id}:confirm")
            if resend is None:
                peer = self._party_resolver(msg.sender)
                claimed = self.checkpoints.init_flow_id(logical)
                if claimed is not None and claimed.startswith("rejected:"):
                    # the original open was REJECTED: repeat the verdict,
                    # never fabricate a Confirm for a responder that was
                    # never spawned
                    self.messaging.send(
                        msg.sender, SESSION_TOPIC,
                        serialize(SessionReject(
                            init.initiator_session_id,
                            claimed[len("rejected:"):],
                        )),
                        msg_id=f"reject-{msg.msg_id}",
                    )
                elif peer is not None and claimed is not None:
                    resend = (
                        peer,
                        SessionConfirm(
                            init.initiator_session_id, _sid_for(claimed, 0)
                        ),
                        f"{claimed}:confirm",
                    )
            if resend is not None:
                # fresh wire id per resend: the ORIGINAL Confirm may have
                # been delivered (and its id remembered by the transport's
                # dedupe) even though the initiator never processed it —
                # a fixed id would be silently swallowed on every retry
                self.send_to(
                    resend[0], resend[1],
                    msg_id=f"{resend[2]}~{Message.fresh_id()[:8]}",
                )
            if ack:
                ack()
            return
        responder = responder_for(init.flow_name)
        peer = self._party_resolver(msg.sender)
        if responder is None or peer is None:
            reason = (
                f"no responder registered for {init.flow_name}"
                if responder is None else f"unknown peer {msg.sender}"
            )
            # overwrite the claim so a RETRANSMITTED init of a rejected
            # open re-sends the rejection — without this marker the
            # duplicate branch would reconstruct a Confirm for a
            # responder that never existed and the initiator would hang
            self.checkpoints.mark_init_rejected(logical, reason)
            self.messaging.send(
                msg.sender, SESSION_TOPIC,
                serialize(SessionReject(init.initiator_session_id, reason)),
                msg_id=f"reject-{msg.msg_id}",
            )
            if ack:
                ack()
            return
        if init.deadline and time.time() >= init.deadline:
            # the initiator's caller already gave up: reject before
            # spawning a responder that would burn verify/notary work on
            # a dead flow (docs/OVERLOAD.md). Marked like any rejection
            # so a retransmitted Init repeats the verdict.
            reason = "flow deadline exceeded before responder start"
            self.checkpoints.mark_init_rejected(logical, reason)
            ov = active_overload()
            if ov is not None:
                ov.note_deadline_shed()
            self.messaging.send(
                msg.sender, SESSION_TOPIC,
                serialize(SessionReject(init.initiator_session_id, reason)),
                msg_id=f"reject-{msg.msg_id}",
            )
            if ack:
                ack()
            return
        self._open_flow_span(flow_id, class_path(responder),
                             responder=True, parent_wire=init.trace)
        cl = active_cluster()
        if cl is not None:
            # the Init hop's delivery stamp: trace id straight off the
            # wire context (authoritative even when unsampled locally)
            cl.note_recv(
                str(self.our_identity.name), msg.sender, logical,
                init.trace.split(":", 1)[0] if init.trace else "",
            )
        fp = active_flowprof()
        if fp is not None:
            fp.open(flow_id, class_path(responder))
        blob = serialize({
            "cls": class_path(responder),
            "fields": {},
            "responder": True,
            # the propagated deadline survives responder park/replay and
            # crash restore exactly like the initiator's (omitted when
            # unset — pre-overload checkpoint shape unchanged)
            **({"deadline": init.deadline} if init.deadline else {}),
        })
        self.checkpoints.add_flow(flow_id, blob, str(self.our_identity.name),
                                  time.time())
        fut: Future = Future()
        ex = _FlowExecutor(
            self, flow_id, [], None, responder_cls=responder,
            init_info={"peer": peer, "peer_sid": init.initiator_session_id,
                       "first": init.first_payload},
            result=fut,
        )
        ex.deadline_t = init.deadline or None
        with self._lock:
            self._flows[flow_id] = ex
            self._results[flow_id] = fut
        if ack:
            ack()  # responder is durable; Init is consumed
        self._enqueue(flow_id)
