"""Persistence: validated-transaction storage and attachment storage.

Parity with the reference's node/.../services/persistence/ —
``DBTransactionStorage`` (map of tx id → blob with a first-write-wins
guarantee and an updates feed the vault subscribes to) and
``NodeAttachmentService`` (NodeAttachmentService.kt — content-addressed
jar/zip blobs, hash-checked on open). SQLite WAL instead of H2/Hibernate;
callback feeds instead of Rx Observables.
"""

from __future__ import annotations

import hashlib
import io
import sqlite3
import threading
import zipfile

from corda_tpu.crypto import SecureHash
from corda_tpu.ledger import SignedTransaction
from corda_tpu.serialization import deserialize, serialize


class DBTransactionStorage:
    """Append-only validated-transactions map (reference:
    DBTransactionStorage.kt; AppendOnlyPersistentMap semantics — a second
    add of the same id is a no-op returning False)."""

    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS transactions ("
            " tx_id BLOB PRIMARY KEY, blob BLOB NOT NULL, ts REAL NOT NULL)"
        )
        self._db.commit()
        self._lock = threading.RLock()
        self._subscribers: list = []

    def add_transaction(self, stx: SignedTransaction) -> bool:
        """Record a validated transaction; returns True if newly stored."""
        blob = serialize(stx)
        with self._lock:
            cur = self._db.execute(
                "INSERT OR IGNORE INTO transactions VALUES (?, ?, julianday('now'))",
                (stx.id.bytes, blob),
            )
            self._db.commit()
            fresh = cur.rowcount == 1
            subs = list(self._subscribers)
        if fresh:
            for cb in subs:
                cb(stx)
        return fresh

    def get(self, tx_id: SecureHash) -> SignedTransaction | None:
        with self._lock:
            row = self._db.execute(
                "SELECT blob FROM transactions WHERE tx_id = ?", (tx_id.bytes,)
            ).fetchone()
        return deserialize(row[0]) if row else None

    def __contains__(self, tx_id: SecureHash) -> bool:
        with self._lock:
            row = self._db.execute(
                "SELECT 1 FROM transactions WHERE tx_id = ?", (tx_id.bytes,)
            ).fetchone()
        return row is not None

    def untrack(self, callback) -> None:
        with self._lock:
            try:
                self._subscribers.remove(callback)
            except ValueError:
                pass

    def track(self, callback) -> list[SignedTransaction]:
        """Subscribe to future additions; returns the current snapshot
        (reference: DataFeed<List<SignedTransaction>, SignedTransaction>)."""
        with self._lock:
            rows = self._db.execute(
                "SELECT blob FROM transactions ORDER BY ts"
            ).fetchall()
            self._subscribers.append(callback)
        return [deserialize(r[0]) for r in rows]

    def count(self) -> int:
        with self._lock:
            return self._db.execute("SELECT COUNT(*) FROM transactions").fetchone()[0]

    def close(self) -> None:
        with self._lock:
            self._db.close()


class Attachment:
    """An opened attachment (reference: core/.../contracts/Attachment —
    id + zip access + signer extraction is out of scope pre-v3)."""

    def __init__(self, attachment_id: SecureHash, data: bytes):
        self.id = attachment_id
        self.data = data

    def open_zip(self) -> zipfile.ZipFile:
        return zipfile.ZipFile(io.BytesIO(self.data))

    def extract_file(self, name: str) -> bytes:
        with self.open_zip() as z:
            return z.read(name)

    @property
    def size(self) -> int:
        return len(self.data)


class AttachmentStorage:
    """Content-addressed attachment store (reference:
    NodeAttachmentService.kt — import computes SHA-256 id, duplicate import
    raises, open re-verifies the hash)."""

    class DuplicateAttachmentError(Exception):
        pass

    class CorruptAttachmentError(Exception):
        pass

    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS attachments ("
            " att_id BLOB PRIMARY KEY, data BLOB NOT NULL)"
        )
        self._db.commit()
        self._lock = threading.RLock()

    def import_attachment(self, data: bytes) -> SecureHash:
        att_id = SecureHash(hashlib.sha256(data).digest())
        with self._lock:
            cur = self._db.execute(
                "INSERT OR IGNORE INTO attachments VALUES (?, ?)",
                (att_id.bytes, data),
            )
            self._db.commit()
            if cur.rowcount == 0:
                raise AttachmentStorage.DuplicateAttachmentError(str(att_id))
        return att_id

    def import_or_get(self, data: bytes) -> SecureHash:
        try:
            return self.import_attachment(data)
        except AttachmentStorage.DuplicateAttachmentError:
            return SecureHash(hashlib.sha256(data).digest())

    def open_attachment(self, att_id: SecureHash) -> Attachment | None:
        with self._lock:
            row = self._db.execute(
                "SELECT data FROM attachments WHERE att_id = ?", (att_id.bytes,)
            ).fetchone()
        if row is None:
            return None
        if hashlib.sha256(row[0]).digest() != att_id.bytes:
            raise AttachmentStorage.CorruptAttachmentError(str(att_id))
        return Attachment(att_id, row[0])

    def has_attachment(self, att_id: SecureHash) -> bool:
        with self._lock:
            return (
                self._db.execute(
                    "SELECT 1 FROM attachments WHERE att_id = ?", (att_id.bytes,)
                ).fetchone()
                is not None
            )

    def close(self) -> None:
        with self._lock:
            self._db.close()


def make_test_attachment(files: dict[str, bytes]) -> bytes:
    """Build a deterministic zip (fixed timestamps) — the attachment-demo
    fixture shape."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for name in sorted(files):
            info = zipfile.ZipInfo(name, date_time=(2017, 1, 1, 0, 0, 0))
            z.writestr(info, files[name])
    return buf.getvalue()
