"""Node process entry point.

Capability parity with the reference's boot path (node/.../Corda.kt:7 main
→ NodeStartup.kt:30: banner, config load, node assembly, run-until-exit).

Standalone processes on one host share a sqlite-file DurableQueueBroker as
the message fabric (the role the reference's Artemis broker + localhost
bridges play in driver deployments); one node additionally runs the
network-map service, and every node registers with it on boot
(reference: registerWithNetworkMapIfConfigured, AbstractNode.kt:245).

    python -m corda_tpu.node.startup --config node.conf --broker shared.db

Config is the HOCON subset of node/.../reference.conf (see config.py).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

BANNER = r"""
   ______                __        ______  __  __
  / ____/___  _________/ /___ _  /_  __/ / / / / /
 / /   / __ \/ ___/ __  / __ `/   / /   / /_/ / / /
/ /___/ /_/ / /  / /_/ / /_/ /   / /   / ____/ /_/
\____/\____/_/   \__,_/\__,_/   /_/   /_/    (_)
        distributed ledger, TPU-native
"""


def build_node(config, broker_path: str, is_network_map: bool = False):
    """Assemble a node over the shared-broker fabric."""
    from corda_tpu.messaging import BrokerMessagingClient, DurableQueueBroker
    from corda_tpu.node.network_map import (
        NetworkMapCache,
        NetworkMapClient,
        NetworkMapServer,
    )
    from corda_tpu.node.node import Node

    from corda_tpu.ledger import CordaX500Name

    import dataclasses as _dc
    import re as _re

    canonical = str(CordaX500Name.parse(config.my_legal_name))
    if config.base_directory == ".":
        # multiple nodes on one host must not share vault/checkpoint
        # files — default the base dir to a per-identity subdirectory
        safe = _re.sub(r"[^A-Za-z0-9._-]+", "_", canonical)
        config = _dc.replace(config, base_directory=f"./{safe}")
    broker = DurableQueueBroker(broker_path)
    messaging = BrokerMessagingClient(broker, canonical)
    cache = NetworkMapCache()
    node = Node(
        config, messaging, network_map=cache,
        persistent=broker_path != ":memory:",
    )
    if is_network_map:
        node.network_map_server = NetworkMapServer(messaging, cache)
    node.network_map_client = NetworkMapClient(messaging, cache)
    node.start()
    if config.network_map_address:
        node.network_map_client.register(config.network_map_address, node.info)
    return node


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="corda-tpu-node",
        description="Run a corda_tpu node (reference: NodeStartup)",
    )
    parser.add_argument("--config", required=True, help="HOCON node config")
    parser.add_argument(
        "--broker", default="broker.db",
        help="shared durable-broker sqlite file (the host message fabric)",
    )
    parser.add_argument(
        "--network-map", action="store_true",
        help="also run the network-map service on this node",
    )
    parser.add_argument("--no-banner", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)-7s [%(name)s] %(message)s",
    )
    if not args.no_banner:
        print(BANNER)

    from corda_tpu.node.config import load_config

    config = load_config(args.config)
    node = build_node(config, args.broker, is_network_map=args.network_map)
    print(f"Node {node.party.name} started. RPC users: "
          f"{[u.username for u in config.rpc_users]}")
    sys.stdout.flush()

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    print("Shutting down…")
    node.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
