"""Node process entry point.

Capability parity with the reference's boot path (node/.../Corda.kt:7 main
→ NodeStartup.kt:30: banner, config load, node assembly, run-until-exit).

Standalone processes on one host share a sqlite-file DurableQueueBroker as
the message fabric (the role the reference's Artemis broker + localhost
bridges play in driver deployments); one node additionally runs the
network-map service, and every node registers with it on boot
(reference: registerWithNetworkMapIfConfigured, AbstractNode.kt:245).

    python -m corda_tpu.node.startup --config node.conf --broker shared.db

Config is the HOCON subset of node/.../reference.conf (see config.py).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

BANNER = r"""
   ______                __        ______  __  __
  / ____/___  _________/ /___ _  /_  __/ / / / / /
 / /   / __ \/ ___/ __  / __ `/   / /   / /_/ / / /
/ /___/ /_/ / /  / /_/ / /_/ /   / /   / ____/ /_/
\____/\____/_/   \__,_/\__,_/   /_/   /_/    (_)
        distributed ledger, TPU-native
"""


def _is_loopback_address(addr: str) -> bool:
    """True when ``host[:port]`` names the local host, including the IPv6
    forms ``[::1]:port`` and bare ``::1`` (rpartition-on-colon would
    mangle those)."""
    if addr.startswith("["):                       # [v6-host]:port
        host = addr[1:].partition("]")[0]
    elif addr.count(":") > 1:                      # bare IPv6 literal
        host = addr
    else:
        host, _, _ = addr.partition(":")
    return host in ("localhost", "127.0.0.1", "::1")


def build_node(
    config, broker_path: str, is_network_map: bool = False,
    fabric_listen: str | None = None, fabric_address: str | None = None,
):
    """Assemble a node over the fabric.

    Three transports (reference: every wire is the node's Artemis broker,
    ArtemisMessagingServer.kt:132-376):

    - ``fabric_listen``: this node EMBEDS the broker and serves it to
      certified peers over the authenticated transport (the
      ArtemisMessagingServer role — required client certs). The node
      itself talks to its in-process broker directly, like the
      reference's NODE_USER local session.
    - ``fabric_address``: connect to a remote node's broker as a
      certified peer (the bridge/client role). The handshake fails —
      before any payload crosses — unless this node's certificate chains
      to the network trust root.
    - neither: open the shared sqlite broker file directly (single-host
      dev ensembles; the pre-secure-fabric mode).

    Certificates auto-provision from the well-known dev CA only when
    ``config.dev_mode`` (reference: devMode certificate generation);
    production mode requires operator-provisioned certificate files.
    """
    from corda_tpu.messaging import BrokerMessagingClient, DurableQueueBroker
    from corda_tpu.node.network_map import (
        NetworkMapCache,
        NetworkMapClient,
        NetworkMapServer,
    )
    from corda_tpu.node.node import Node

    from corda_tpu.ledger import CordaX500Name

    import dataclasses as _dc
    import re as _re

    canonical = str(CordaX500Name.parse(config.my_legal_name))
    if config.base_directory == ".":
        # multiple nodes on one host must not share vault/checkpoint
        # files — default the base dir to a per-identity subdirectory
        safe = _re.sub(r"[^A-Za-z0-9._-]+", "_", canonical)
        config = _dc.replace(config, base_directory=f"./{safe}")

    if fabric_listen and fabric_address:
        raise ValueError(
            "--fabric-listen and --fabric are mutually exclusive: a node "
            "either embeds the broker or connects to a remote one"
        )
    # RPC rides the fabric; a non-localhost rpcAddress without the
    # authenticated transport would send credentials in clear (the
    # reference always rides TLS — ArtemisMessagingServer required
    # client certs). Dev ensembles keep rpcAddress on localhost.
    if (
        config.rpc_address
        and not _is_loopback_address(config.rpc_address)
        and not (fabric_listen or fabric_address)
    ):
        raise ValueError(
            f"rpcAddress {config.rpc_address!r} is not localhost: serving "
            "RPC off-host requires the secure fabric (--fabric-listen / "
            "--fabric), otherwise credentials cross the wire in clear"
        )
    fabric_server = None
    keypair = None
    if fabric_listen or fabric_address:
        from corda_tpu.node.certificates import node_certificates

        ident = node_certificates(
            config.base_directory, canonical, dev_mode=config.dev_mode
        )
        keypair = ident.keypair
        if fabric_listen:
            from corda_tpu.messaging import SecureBrokerServer

            broker = DurableQueueBroker(broker_path)
            host, _, port = fabric_listen.rpartition(":")
            fabric_server = SecureBrokerServer(
                broker, ident.certificate, ident.keypair.private,
                ident.trust_root, host=host or "127.0.0.1", port=int(port),
            )
            fabric = broker  # embedded broker: local direct session
        else:
            from corda_tpu.messaging import SecureFabricClient

            fabric = SecureFabricClient(
                fabric_address, ident.certificate, ident.keypair.private,
                ident.trust_root,
            )
    else:
        fabric = DurableQueueBroker(broker_path)
    messaging = BrokerMessagingClient(fabric, canonical)
    cache = NetworkMapCache()
    node = Node(
        config, messaging, network_map=cache, keypair=keypair,
        persistent=broker_path != ":memory:",
    )
    node.fabric_server = fabric_server
    node.fabric_client = fabric if fabric_address else None
    if is_network_map:
        node.network_map_server = NetworkMapServer(messaging, cache)
    node.network_map_client = NetworkMapClient(messaging, cache)
    node.start()
    if config.network_map_address:
        node.network_map_client.register(config.network_map_address, node.info)
    return node


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="corda-tpu-node",
        description="Run a corda_tpu node (reference: NodeStartup)",
    )
    parser.add_argument("--config", required=True, help="HOCON node config")
    parser.add_argument(
        "--broker", default="broker.db",
        help="shared durable-broker sqlite file (the host message fabric)",
    )
    parser.add_argument(
        "--network-map", action="store_true",
        help="also run the network-map service on this node",
    )
    parser.add_argument(
        "--fabric-listen", default=None, metavar="HOST:PORT",
        help="embed the broker and serve it to certified peers over the "
             "mutually-authenticated transport (ArtemisMessagingServer role)",
    )
    parser.add_argument(
        "--fabric", default=None, metavar="HOST:PORT", dest="fabric_address",
        help="connect to a remote node's broker as a certified peer "
             "instead of opening the sqlite fabric file",
    )
    parser.add_argument("--no-banner", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)-7s [%(name)s] %(message)s",
    )
    # operator stack dump on demand: `kill -USR1 <pid>` writes every
    # thread's Python stack to stderr (the node log) — the first tool for
    # a wedged node (reference role: jstack on a JVM node)
    import faulthandler
    import signal

    try:
        faulthandler.register(signal.SIGUSR1)
    except (AttributeError, ValueError):
        pass  # platform without SIGUSR1; non-main-thread registration
    if not args.no_banner:
        print(BANNER)

    from corda_tpu.node.config import load_config

    config = load_config(args.config)
    node = build_node(
        config, args.broker, is_network_map=args.network_map,
        fabric_listen=args.fabric_listen, fabric_address=args.fabric_address,
    )
    if node.fabric_server is not None:
        print(f"Secure fabric listening on "
              f"{node.fabric_server.address[0]}:{node.fabric_server.address[1]}")
    print(f"Node {node.party.name} started. RPC users: "
          f"{[u.username for u in config.rpc_users]}")
    sys.stdout.flush()

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    print("Shutting down…")
    node.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
