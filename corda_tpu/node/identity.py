"""Identity and key-management services.

Parity with the reference's node/.../services/identity/
(``InMemoryIdentityService``/``PersistentIdentityService`` — cert-validating
name↔key registry, anonymous-identity resolution) and node/.../services/keys/
(``KeyManagementService`` — fresh-key issuance, signing by owned key;
``freshCertificate`` in KMSUtils.kt issuing a child certificate off the node
identity for confidential identities).
"""

from __future__ import annotations

import threading

from corda_tpu.crypto import (
    DEFAULT_SIGNATURE_SCHEME,
    CryptoError,
    KeyPair,
    PublicKey,
    SecureHash,
    SignatureMetadata,
    TransactionSignature,
    generate_keypair,
    sign_tx_id,
)
from corda_tpu.ledger import (
    AnonymousParty,
    CordaX500Name,
    NameKeyCertificate,
    Party,
    PartyAndCertificate,
)


class UnknownAnonymousPartyError(Exception):
    pass


class IdentityService:
    """Well-known and confidential identity registry (reference:
    InMemoryIdentityService.kt / PersistentIdentityService.kt).

    Registration verifies the certificate path against the trust root when
    one is configured — an invalid chain is rejected, the property the
    reference enforces via CertPathValidator.
    """

    def __init__(self, trust_root_key: PublicKey | None = None,
                 well_known: list[PartyAndCertificate] | None = None):
        self._trust_root_key = trust_root_key
        self._lock = threading.RLock()
        self._by_key: dict[PublicKey, PartyAndCertificate] = {}
        self._by_name: dict[CordaX500Name, PartyAndCertificate] = {}
        # anonymous key → well-known party it belongs to
        self._anonymous: dict[PublicKey, Party] = {}
        self._anon_certs: dict[PublicKey, NameKeyCertificate] = {}
        for pc in well_known or []:
            self.register_identity(pc)

    def register_identity(self, pc: PartyAndCertificate) -> None:
        if self._trust_root_key is not None and not pc.verify(self._trust_root_key):
            raise CryptoError(f"certificate path for {pc.party} fails validation")
        with self._lock:
            self._by_key[pc.party.owning_key] = pc
            self._by_name[pc.party.name] = pc

    def register_anonymous_identity(
        self, anonymous: AnonymousParty, well_known: Party,
        certificate: NameKeyCertificate | None = None,
    ) -> None:
        """Bind a confidential key to its well-known owner. When a
        certificate is supplied it must be issued by the owner's key (the
        reference's swap-identities verification)."""
        if certificate is not None:
            if (certificate.subject_key != anonymous.owning_key
                    or certificate.issuer_key != well_known.owning_key
                    or certificate.name != well_known.name
                    or not certificate.verify()):
                raise CryptoError("anonymous identity certificate invalid")
        with self._lock:
            self._anonymous[anonymous.owning_key] = well_known
            if certificate is not None:
                self._anon_certs[anonymous.owning_key] = certificate

    def anonymous_binding(self, anonymous) -> tuple | None:
        """(anonymous, well_known, certificate) for a registered
        confidential key we hold the certificate for — the unit
        IdentitySyncFlow ships to counterparties."""
        with self._lock:
            well_known = self._anonymous.get(anonymous.owning_key)
            cert = self._anon_certs.get(anonymous.owning_key)
        if well_known is None or cert is None:
            return None
        return (anonymous, well_known, cert)

    def party_from_key(self, key: PublicKey) -> Party | None:
        with self._lock:
            pc = self._by_key.get(key)
            return pc.party if pc else None

    def party_from_name(self, name: CordaX500Name) -> Party | None:
        with self._lock:
            pc = self._by_name.get(name)
            return pc.party if pc else None

    def certificate_from_key(self, key: PublicKey) -> PartyAndCertificate | None:
        with self._lock:
            return self._by_key.get(key)

    def well_known_party_from_anonymous(self, party) -> Party | None:
        """Resolve AnonymousParty → Party (reference:
        IdentityService.wellKnownPartyFromAnonymous)."""
        if isinstance(party, Party):
            return self.party_from_key(party.owning_key) or party
        with self._lock:
            known = self._anonymous.get(party.owning_key)
        if known is not None:
            return known
        return self.party_from_key(party.owning_key)

    def require_well_known(self, party) -> Party:
        resolved = self.well_known_party_from_anonymous(party)
        if resolved is None:
            raise UnknownAnonymousPartyError(str(party))
        return resolved

    def all_identities(self) -> list[PartyAndCertificate]:
        with self._lock:
            return list(self._by_key.values())


class KeyManagementService:
    """Owns the node's signing keys (reference: KeyManagementService +
    E2ETestKeyManagementService / PersistentKeyManagementService).

    ``fresh_key_and_cert`` issues a new confidential key with a certificate
    signed by the node's identity key (reference: KMSUtils.freshCertificate)
    and registers it with the identity service.
    """

    def __init__(self, initial_keys: list[KeyPair] | None = None,
                 identity_service: IdentityService | None = None):
        self._lock = threading.RLock()
        self._keys: dict[PublicKey, KeyPair] = {}
        self._identity_service = identity_service
        for kp in initial_keys or []:
            self._keys[kp.public] = kp

    @property
    def keys(self) -> set[PublicKey]:
        with self._lock:
            return set(self._keys)

    def add_key(self, kp: KeyPair) -> None:
        with self._lock:
            self._keys[kp.public] = kp

    def fresh_key(self, scheme_id: int = DEFAULT_SIGNATURE_SCHEME) -> PublicKey:
        kp = generate_keypair(scheme_id)
        self.add_key(kp)
        return kp.public

    def fresh_key_and_cert(
        self, identity: PartyAndCertificate, identity_keypair: KeyPair,
        scheme_id: int = DEFAULT_SIGNATURE_SCHEME,
    ) -> tuple[AnonymousParty, NameKeyCertificate]:
        pub = self.fresh_key(scheme_id)
        cert = NameKeyCertificate.issue(
            identity.party.name, pub, identity_keypair.public,
            identity_keypair.private,
        )
        anon = AnonymousParty(pub)
        if self._identity_service is not None:
            self._identity_service.register_anonymous_identity(
                anon, identity.party, cert
            )
        return anon, cert

    def fresh_confidential_identity(
        self, identity: Party, scheme_id: int = DEFAULT_SIGNATURE_SCHEME,
    ) -> tuple[AnonymousParty, NameKeyCertificate]:
        """Mint a fresh anonymous key certified by ``identity``'s key,
        which must be one of ours (the public face of fresh_key_and_cert
        for swap-identities flows)."""
        kp = self._require(identity.owning_key)
        return self.fresh_key_and_cert(
            PartyAndCertificate(identity, ()), kp, scheme_id
        )

    def _require(self, key: PublicKey) -> KeyPair:
        with self._lock:
            kp = self._keys.get(key)
        if kp is None:
            raise CryptoError(f"no private key known for {key.to_string_short()}")
        return kp

    def sign(self, tx_id: SecureHash, key: PublicKey) -> TransactionSignature:
        kp = self._require(key)
        return sign_tx_id(kp.private, kp.public, tx_id)

    def sign_bytes(self, data: bytes, key: PublicKey) -> bytes:
        from corda_tpu.crypto import sign as raw_sign

        return raw_sign(self._require(key).private, data)

    def filter_my_keys(self, candidates) -> list[PublicKey]:
        with self._lock:
            return [k for k in candidates if k in self._keys]
