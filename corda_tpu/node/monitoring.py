"""Metrics registry.

Parity with the reference's Codahale/Dropwizard ``MonitoringService``
(node/.../services/api/MonitoringService.kt:11) and the verification
metrics seam (OutOfProcessTransactionVerifierService.kt:37-48 —
duration timer, success/failure meters, in-flight gauge). Plain-Python,
thread-safe, snapshot-able for the RPC/shell observability surface.
"""

from __future__ import annotations

import math
import threading
import time


class Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: int = 1) -> None:
        self.inc(-n)

    @property
    def count(self) -> int:
        return self._v

    def snapshot(self) -> dict:
        return {"type": "counter", "count": self._v}


class Gauge:
    """A gauge reads a callable at snapshot time (in-flight style)."""

    def __init__(self, fn):
        self._fn = fn

    @property
    def value(self):
        return self._fn()

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self._fn()}


class Meter:
    """Event rate: total count + exponentially-weighted 1-minute rate."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._count = 0
        self._rate = 0.0
        self._last = clock()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            now = self._clock()
            dt = now - self._last
            if dt > 0:
                alpha = 1.0 - math.exp(-dt / 60.0)
                inst = n / dt
                self._rate += alpha * (inst - self._rate)
                self._last = now
            self._count += n

    @property
    def count(self) -> int:
        return self._count

    @property
    def one_minute_rate(self) -> float:
        return self._rate

    def snapshot(self) -> dict:
        return {"type": "meter", "count": self._count, "m1_rate": self._rate}


class Timer:
    """Duration histogram (count / mean / min / max / last)."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0
        self._min = math.inf
        self._max = 0.0
        self._last = 0.0

    class _Ctx:
        def __init__(self, timer):
            self._timer = timer

        def __enter__(self):
            self._t0 = self._timer._clock()
            return self

        def __exit__(self, *exc):
            self._timer.update(self._timer._clock() - self._t0)
            return False

    def time(self) -> "_Ctx":
        return Timer._Ctx(self)

    def update(self, seconds: float) -> None:
        with self._lock:
            self._count += 1
            self._total += seconds
            self._min = min(self._min, seconds)
            self._max = max(self._max, seconds)
            self._last = seconds

    @property
    def count(self) -> int:
        return self._count

    @property
    def mean(self) -> float:
        return self._total / self._count if self._count else 0.0

    def snapshot(self) -> dict:
        return {
            "type": "timer",
            "count": self._count,
            "mean_s": self.mean,
            "min_s": 0.0 if math.isinf(self._min) else self._min,
            "max_s": self._max,
            "last_s": self._last,
        }


class MetricRegistry:
    """Named metric store (reference: com.codahale.metrics.MetricRegistry
    held by MonitoringService)."""

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def gauge(self, name: str, fn=None) -> Gauge:
        if fn is not None:
            with self._lock:
                self._metrics[name] = Gauge(fn)
        return self._metrics[name]

    def snapshot(self) -> dict:
        with self._lock:
            return {k: v.snapshot() for k, v in sorted(self._metrics.items())}

    def section(self, prefix: str) -> dict:
        """Snapshot of the metrics under one dotted prefix, keys
        relativized (``section("serving.")`` → ``{"batches": ...}``)."""
        snap = self.snapshot()
        return {
            k[len(prefix):]: v for k, v in snap.items()
            if k.startswith(prefix)
        }


# Process-global registry for cross-cutting health events that happen
# below any service object holding its own registry — currently the
# verifier's device→host failover counters (``verifier.device_failover``,
# ``verifier.device_failover_rows``). Node services with their own
# MonitoringService keep using per-node registries; this one is the
# operator's "did anything degrade in this process" surface.
_process_registry = MetricRegistry()


def node_metrics() -> MetricRegistry:
    return _process_registry


def monitoring_snapshot() -> dict:
    """The process-wide observability snapshot, sectioned for the RPC/shell
    surface: ``serving`` holds the device scheduler's queue/batch/shed
    counters and gauges (corda_tpu/serving — queue depth & rows, wait
    time, batch occupancy & latency, shed/rejected counts, failovers),
    ``process`` the remaining cross-cutting metrics (e.g. the verifier's
    ``device_failover`` counters)."""
    return {
        "serving": _process_registry.section("serving."),
        "process": {
            k: v for k, v in _process_registry.snapshot().items()
            if not k.startswith("serving.")
        },
    }
