"""Metrics registry.

Parity with the reference's Codahale/Dropwizard ``MonitoringService``
(node/.../services/api/MonitoringService.kt:11) and the verification
metrics seam (OutOfProcessTransactionVerifierService.kt:37-48 —
duration timer, success/failure meters, in-flight gauge). Plain-Python,
thread-safe, snapshot-able for the RPC/shell observability surface.
"""

from __future__ import annotations

import math
import random
import threading
import time


class QuantileReservoir:
    """Fixed-size uniform sample (Vitter's algorithm R) feeding the
    p50/p95/p99 fields of Timer/Meter snapshots. 512 slots bounds memory
    per metric while keeping the p99 estimate useful at the batch counts
    the serving scheduler sees; the RNG is private and seeded so snapshot
    quantiles are reproducible for a deterministic driven sequence.

    NOT thread-safe on its own — the owning metric's lock guards it."""

    __slots__ = ("_size", "_values", "_seen", "_rng", "_exemplars")

    def __init__(self, size: int = 512, seed: int = 0x0B5E):
        self._size = size
        self._values: list[float] = []
        self._seen = 0
        self._rng = random.Random(seed)
        self._exemplars: list[str] = []

    def update(self, value: float, exemplar: str = "") -> None:
        self._seen += 1
        if len(self._values) < self._size:
            self._values.append(value)
            self._exemplars.append(exemplar)
        else:
            j = self._rng.randrange(self._seen)
            if j < self._size:
                self._values[j] = value
                self._exemplars[j] = exemplar

    def quantiles(self, qs=(0.5, 0.95, 0.99)) -> list[float]:
        """Nearest-rank quantiles over the current sample (0.0 each when
        empty — snapshots stay numeric for the exposition layer)."""
        if not self._values:
            return [0.0] * len(qs)
        ordered = sorted(self._values)
        n = len(ordered)
        return [ordered[min(n - 1, int(q * n))] for q in qs]

    def quantiles_with_exemplars(self, qs=(0.5, 0.95, 0.99)) -> list[tuple]:
        """Like ``quantiles`` but each entry is ``(value, exemplar)`` —
        the exemplar stamped on the reservoir sample at the quantile rank
        (empty string when that sample carried none). Exemplars ride the
        sample they arrived with through replacement, so a quantile's
        exemplar is always a trace that really took that long."""
        if not self._values:
            return [(0.0, "")] * len(qs)
        order = sorted(range(len(self._values)),
                       key=lambda i: self._values[i])
        n = len(order)
        out = []
        for q in qs:
            i = order[min(n - 1, int(q * n))]
            out.append((self._values[i], self._exemplars[i]))
        return out


class Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: int = 1) -> None:
        self.inc(-n)

    @property
    def count(self) -> int:
        return self._v

    def snapshot(self) -> dict:
        return {"type": "counter", "count": self._v}


class Gauge:
    """A gauge reads a callable at snapshot time (in-flight style)."""

    def __init__(self, fn):
        self._fn = fn

    @property
    def value(self):
        return self._fn()

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self._fn()}


class Meter:
    """Event rate: total count + exponentially-weighted 1-minute rate,
    plus a reservoir over per-mark sizes (``mark(n)`` records ``n``) —
    p50/p95/p99 of e.g. rows-per-request for the ``serving.rows`` meter.

    Burst accounting: marks arriving with ``dt == 0`` (several requests
    inside one clock tick) fold into ``_pending`` and count toward the
    NEXT nonzero-dt rate sample — previously only the final mark's ``n``
    was treated as the interval's events, understating ``m1_rate`` under
    bursts by up to the burst size."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._count = 0
        self._rate = 0.0
        self._last = clock()
        self._pending = 0
        self._reservoir = QuantileReservoir()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            now = self._clock()
            dt = now - self._last
            self._count += n
            self._pending += n
            self._reservoir.update(float(n))
            if dt > 0:
                alpha = 1.0 - math.exp(-dt / 60.0)
                inst = self._pending / dt
                self._rate += alpha * (inst - self._rate)
                self._last = now
                self._pending = 0

    @property
    def count(self) -> int:
        return self._count

    @property
    def one_minute_rate(self) -> float:
        return self._rate

    def snapshot(self) -> dict:
        with self._lock:
            p50, p95, p99 = self._reservoir.quantiles()
            return {
                "type": "meter", "count": self._count, "m1_rate": self._rate,
                "p50": p50, "p95": p95, "p99": p99,
            }


class Timer:
    """Duration histogram (count / mean / min / max / last) with a
    fixed-size reservoir exposing p50/p95/p99 — the tail-attribution
    fields the serving/verifier/notary timers report (a mean hides
    exactly the queueing effects the cross-layer traces exist to find)."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0
        self._min = math.inf
        self._max = 0.0
        self._last = 0.0
        self._reservoir = QuantileReservoir()
        self._tap = None

    class _Ctx:
        def __init__(self, timer):
            self._timer = timer

        def __enter__(self):
            self._t0 = self._timer._clock()
            return self

        def __exit__(self, *exc):
            self._timer.update(self._timer._clock() - self._t0)
            return False

    def time(self) -> "_Ctx":
        return Timer._Ctx(self)

    def update(self, seconds: float, *, exemplar: str | None = None) -> None:
        with self._lock:
            self._count += 1
            self._total += seconds
            self._min = min(self._min, seconds)
            self._max = max(self._max, seconds)
            self._last = seconds
            self._reservoir.update(seconds, exemplar or "")
        # Tap outside the lock: one attribute read when no tap is set
        # (the off-by-default overhead contract), and a tap callback can
        # never deadlock against a concurrent snapshot.
        tap = self._tap
        if tap is not None:
            tap(seconds)

    def set_tap(self, fn) -> None:
        """Install (or clear, with None) a per-update observer. At most
        one tap — the telemetry timeline owns this seam; it receives the
        raw duration on the updating thread and must be cheap."""
        self._tap = fn

    def quantiles_with_exemplars(self, qs=(0.5, 0.95, 0.99)) -> list[tuple]:
        with self._lock:
            return self._reservoir.quantiles_with_exemplars(qs)

    @property
    def count(self) -> int:
        return self._count

    @property
    def mean(self) -> float:
        return self._total / self._count if self._count else 0.0

    def quantiles(self, qs=(0.5, 0.95, 0.99)) -> list[float]:
        with self._lock:
            return self._reservoir.quantiles(qs)

    def snapshot(self) -> dict:
        with self._lock:
            qe = self._reservoir.quantiles_with_exemplars()
            (p50, e50), (p95, e95), (p99, e99) = qe
            out = {
                "type": "timer",
                "count": self._count,
                "mean_s": (
                    self._total / self._count if self._count else 0.0
                ),
                "min_s": 0.0 if math.isinf(self._min) else self._min,
                "max_s": self._max,
                "last_s": self._last,
                "total_s": self._total,
                "p50_s": p50,
                "p95_s": p95,
                "p99_s": p99,
            }
            # Exemplars appear ONLY when at least one sample carried a
            # trace id — an un-stamped timer's snapshot shape is
            # bit-identical to the pre-exemplar era (tests pin it).
            if e50 or e95 or e99:
                out["exemplars"] = {
                    k: v for k, v in
                    (("p50_s", e50), ("p95_s", e95), ("p99_s", e99)) if v
                }
            return out


class MetricRegistry:
    """Named metric store (reference: com.codahale.metrics.MetricRegistry
    held by MonitoringService)."""

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def gauge(self, name: str, fn=None) -> Gauge:
        """Register (``fn`` given) or read a gauge. A read before any
        registration returns a TRANSIENT placeholder gauge reading None —
        never a bare KeyError from registry internals — and the read holds
        the lock like every other accessor (an unlocked dict read raced
        concurrent registrations). The placeholder is deliberately NOT
        stored: registering it would poison the name for a later
        ``counter(name)``/``meter(name)``/``timer(name)`` writer, whose
        ``_get`` would hand back the Gauge and crash the writing thread
        (the serving dispatcher, for one) on ``.inc()``."""
        with self._lock:
            if fn is not None:
                self._metrics[name] = Gauge(fn)
            m = self._metrics.get(name)
            if m is None:
                return Gauge(lambda: None)
            if not isinstance(m, Gauge):
                raise TypeError(
                    f"metric {name!r} is a {type(m).__name__}, not a Gauge"
                )
            return m

    def snapshot(self) -> dict:
        with self._lock:
            return {k: v.snapshot() for k, v in sorted(self._metrics.items())}

    def section(self, prefix: str) -> dict:
        """Snapshot of the metrics under one dotted prefix, keys
        relativized (``section("serving.")`` → ``{"batches": ...}``)."""
        snap = self.snapshot()
        return {
            k[len(prefix):]: v for k, v in snap.items()
            if k.startswith(prefix)
        }


# Process-global registry for cross-cutting health events that happen
# below any service object holding its own registry — currently the
# verifier's device→host failover counters (``verifier.device_failover``,
# ``verifier.device_failover_rows``). Node services with their own
# MonitoringService keep using per-node registries; this one is the
# operator's "did anything degrade in this process" surface.
_process_registry = MetricRegistry()


def node_metrics() -> MetricRegistry:
    return _process_registry


def monitoring_snapshot() -> dict:
    """The process-wide observability snapshot, sectioned for the RPC/shell
    surface: ``serving`` holds the device scheduler's queue/batch/shed
    counters and gauges (corda_tpu/serving — queue depth & rows, wait
    time, batch occupancy & latency, pad waste & fill ratio, shed/rejected
    counts, failovers), ``profiler`` the kernel profiler's registry
    mirror (compile/execute timers, row/pad counters — empty until the
    first profiled dispatch, and retaining the last profiled run's
    numbers after the profiler is disabled; the per-kernel detail is
    ``CordaRPCOps.profiler_snapshot()``), ``devices`` the per-device
    telemetry registry (observability/devicemon — ``{"enabled": false}``
    while off), ``slo`` the SLO monitor's evaluated objectives
    (observability/slo, same off-marker contract), ``resilience`` the
    self-healing dispatch policy's quarantine/breaker state machines
    (serving/resilience — same off-marker contract), ``durability`` the
    crash-consistent persistence tier's WAL/replay/recovery registries
    (corda_tpu/durability — ``{"enabled": false}`` until the first
    DurableStore exists in the process), ``flowprof`` the per-flow
    critical-path phase accounting waterfall (observability/flowprof —
    ``{"enabled": false}`` while off), ``sampler`` the wall-clock stack
    sampler's folded-stack dump (observability/sampler, same off-marker
    contract), ``net`` the per-edge network-path telemetry ledgers
    (messaging/netstats — delivery/transit/retransmit counts and
    partition-suspect state, ``{"enabled": false}`` while off),
    ``cluster`` the cross-node hop recorder's status
    (observability/cluster, same off-marker contract), ``overload`` the
    overload governor's admission/retry-budget/deadline-shed state
    (flows/overload — ``{"enabled": false}`` while off), ``statestore``
    the device-resident sharded state store's table stats + probe/spill
    registries (corda_tpu/statestore — ``{"enabled": false}`` until the
    first device table exists), ``timeline`` the ring-buffer telemetry
    recorder's sampled series (observability/timeseries —
    ``{"enabled": false}`` while off), ``contention`` the lock-
    contention observatory's per-site wait/hold tables and wait edges
    (observability/contention — ``{"enabled": false}`` while off),
    ``causal`` the causal profiler's last speedup ledger
    (observability/causal — ``{"enabled": false}`` until a run),
    ``process`` the remaining
    cross-cutting metrics (e.g. the verifier's ``device_failover``
    counters)."""
    from corda_tpu.durability import durability_section
    from corda_tpu.flows.overload import overload_section
    from corda_tpu.messaging.netstats import netstats_section
    from corda_tpu.observability.causal import causal_section
    from corda_tpu.observability.cluster import cluster_section
    from corda_tpu.observability.contention import contention_section
    from corda_tpu.observability.devicemon import devices_section
    from corda_tpu.observability.flowprof import flowprof_section
    from corda_tpu.observability.sampler import sampler_section
    from corda_tpu.observability.slo import slo_section
    from corda_tpu.observability.timeseries import timeline_section
    from corda_tpu.serving.resilience import resilience_section
    from corda_tpu.statestore import statestore_section

    return {
        "serving": _process_registry.section("serving."),
        "profiler": _process_registry.section("profiler."),
        "devices": devices_section(),
        "slo": slo_section(),
        "resilience": resilience_section(),
        "durability": durability_section(),
        "flowprof": flowprof_section(),
        "sampler": sampler_section(),
        "net": netstats_section(),
        "cluster": cluster_section(),
        "overload": overload_section(),
        "statestore": statestore_section(),
        "timeline": timeline_section(),
        "contention": contention_section(),
        "causal": causal_section(),
        "process": {
            k: v for k, v in _process_registry.snapshot().items()
            if not (k.startswith("serving.") or k.startswith("profiler.")
                    or k.startswith("durability.")
                    or k.startswith("replay.")
                    or k.startswith("recovery.")
                    or k.startswith("flowprof.")
                    or k.startswith("sampler.")
                    or k.startswith("net.")
                    or k.startswith("cluster.")
                    or k.startswith("overload.")
                    or k.startswith("retry_budget.")
                    or k.startswith("admission.")
                    or k.startswith("statestore.")
                    or k.startswith("timeline.")
                    or k.startswith("contention.")
                    or k.startswith("causal."))
        },
    }
