"""Typed node configuration.

Parity with the reference's Typesafe-HOCON config stack
(node/.../services/config/NodeConfiguration.kt:17-106 — ``verifierType``,
``notary { validating, raft{...}, bftSMaRt{...} }``, rpcUsers, devMode,
``messageRedeliveryDelaySeconds``; defaults from
node/src/main/resources/reference.conf). Re-designed as frozen dataclasses
loaded from a HOCON-compatible subset (JSON superset: ``key = value``,
``key { ... }`` nesting, ``//``/``#`` comments, unquoted scalars) so the
reference's config files port mechanically.
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import hmac
import json
import os
import re
from pathlib import Path


class VerifierType(enum.Enum):
    """Reference: enum VerifierType { InMemory, OutOfProcess }
    (NodeConfiguration.kt:106) plus the TPU batching tier this framework
    adds as the production default."""

    InMemory = "InMemory"
    OutOfProcess = "OutOfProcess"
    DeviceBatched = "DeviceBatched"


@dataclasses.dataclass(frozen=True)
class RaftConfig:
    """Reference: RaftConfig (NodeConfiguration.kt:45)."""

    node_address: str
    cluster_addresses: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class BFTConfig:
    """Reference: BFTSMaRtConfiguration (NodeConfiguration.kt:51) — replica
    id plus the debug race-exposure flag."""

    replica_id: int
    cluster_addresses: tuple[str, ...] = ()
    expose_races: bool = False


@dataclasses.dataclass(frozen=True)
class NotaryConfig:
    """Reference: NotaryConfig (NodeConfiguration.kt:39) — exactly one of
    raft/bft may be set; validating controls tear-off vs full verification."""

    validating: bool = False
    raft: RaftConfig | None = None
    bft: BFTConfig | None = None

    def __post_init__(self):
        if self.raft is not None and self.bft is not None:
            raise ValueError("notary config cannot be both raft and bftSMaRt")


@dataclasses.dataclass(frozen=True)
class RpcUser:
    """An RPC credential entry (reference: NodeConfiguration.kt rpcUsers).

    ``password`` holds either a plaintext secret (dev ensembles) or a
    salted-hash entry of the form ``pbkdf2$<iters>$<salt_hex>$<hash_hex>``
    produced by :func:`hash_rpc_password` — the at-rest form a production
    node.conf should carry. Either way, candidate checks go through
    :meth:`check_password`, which compares in constant time.
    """

    username: str
    password: str
    permissions: tuple[str, ...] = ()

    def check_password(self, candidate: str) -> bool:
        stored = self.password
        if stored.startswith("pbkdf2$"):
            try:
                _, iters, salt_hex, hash_hex = stored.split("$")
                expected = bytes.fromhex(hash_hex)
                derived = hashlib.pbkdf2_hmac(
                    "sha256", candidate.encode(), bytes.fromhex(salt_hex),
                    int(iters),
                )
            except (ValueError, TypeError):
                return False
            return hmac.compare_digest(derived, expected)
        return hmac.compare_digest(stored.encode(), candidate.encode())


def _plain_password(value: str) -> str:
    """Guard the shared-field encoding: a plaintext ``password`` that starts
    with the hash-entry prefix would silently become uncheckable (every
    candidate takes the hash branch and fails) — reject it at load time."""
    if value.startswith("pbkdf2$"):
        raise ValueError(
            "plaintext rpcUsers password may not start with 'pbkdf2$'; "
            "if this is a hash entry, put it under the passwordHash key"
        )
    return value


def hash_rpc_password(password: str, *, iterations: int = 120_000,
                      _salt: bytes | None = None) -> str:
    """Produce a salted-hash rpcUsers entry for node.conf (``passwordHash``)."""
    salt = _salt if _salt is not None else os.urandom(16)
    derived = hashlib.pbkdf2_hmac(
        "sha256", password.encode(), salt, iterations
    )
    return f"pbkdf2${iterations}${salt.hex()}${derived.hex()}"


@dataclasses.dataclass(frozen=True)
class NodeConfiguration:
    """The typed root (reference: NodeConfiguration.kt:17-36 +
    FullNodeConfiguration :63)."""

    my_legal_name: str
    base_directory: str = "."
    p2p_address: str = "localhost:10002"
    rpc_address: str | None = None
    notary: NotaryConfig | None = None
    verifier_type: VerifierType = VerifierType.DeviceBatched
    rpc_users: tuple[RpcUser, ...] = ()
    dev_mode: bool = True
    network_map_address: str | None = None
    message_redelivery_delay_seconds: float = 30.0
    flow_timeout_seconds: float = 120.0
    verification_batch_max: int = 1024
    verification_window_ms: float = 5.0
    database_path: str | None = None  # None → <base_directory>/node.db
    # python packages imported at boot so their contracts/flows/serializers
    # register — the reference's CordappLoader plugins-directory scan
    # (node/.../internal/cordapp/CordappLoader.kt:41) as explicit config
    cordapp_packages: tuple[str, ...] = ()
    # the reference's plugins-directory scan: every module/package in this
    # directory loads as an app at boot (node/cordapp.py CordappLoader)
    cordapp_directory: str | None = None
    # device-mesh fan-out for signature batches (SURVEY §2.9 P3): None =
    # auto (on when >1 accelerator device is visible), true/false forces
    mesh_fan_out: bool | None = None

    @property
    def db_path(self) -> str:
        if self.database_path is not None:
            return self.database_path
        return str(Path(self.base_directory) / "node.db")


# --- HOCON-subset parser -----------------------------------------------------

_COMMENT = re.compile(r"(?m)(//|#).*$")


class _Hocon:
    """Recursive-descent parser for the HOCON subset the reference's config
    files use: ``key = value`` / ``key : value`` / ``key { ... }`` nesting,
    optional commas, quoted or bare keys, JSON values plus unquoted strings."""

    def __init__(self, text: str):
        self.s = _COMMENT.sub("", text)
        self.i = 0

    def _ws(self):
        while self.i < len(self.s) and self.s[self.i] in " \t\r\n,":
            self.i += 1

    def _peek(self) -> str:
        self._ws()
        return self.s[self.i] if self.i < len(self.s) else ""

    def parse(self) -> dict:
        if self._peek() == "{":
            return self._object()
        return self._object(bare=True)

    def _object(self, bare: bool = False) -> dict:
        if not bare:
            self.i += 1  # consume '{'
        out: dict = {}
        while True:
            c = self._peek()
            if c == "" or c == "}":
                if c == "}":
                    self.i += 1
                return out
            key = self._key()
            c = self._peek()
            if c in "=:":
                self.i += 1
                out[key] = self._value()
            elif c == "{":
                out[key] = self._object()
            else:
                raise ValueError(f"expected = : or {{ after key {key!r} at {self.i}")

    def _key(self) -> str:
        if self._peek() == '"':
            return self._string()
        m = re.match(r"[\w.$-]+", self.s[self.i:])
        if not m:
            raise ValueError(f"bad key at offset {self.i}")
        self.i += m.end()
        return m.group(0)

    def _string(self) -> str:
        m = re.match(r'"((?:[^"\\]|\\.)*)"', self.s[self.i:])
        if not m:
            raise ValueError(f"unterminated string at {self.i}")
        self.i += m.end()
        return json.loads('"' + m.group(1) + '"')

    def _value(self):
        c = self._peek()
        if c == "{":
            return self._object()
        if c == "[":
            return self._array()
        if c == '"':
            return self._string()
        # bare scalar: runs to end-of-line / comma / closer
        m = re.match(r"[^\n,\]}]*", self.s[self.i:])
        raw = m.group(0).strip()
        self.i += m.end()
        if re.fullmatch(r"-?\d+", raw):
            return int(raw)
        if re.fullmatch(r"-?\d+\.\d*([eE][+-]?\d+)?", raw):
            return float(raw)
        if raw in ("true", "false"):
            return raw == "true"
        if raw == "null":
            return None
        return raw

    def _array(self) -> list:
        self.i += 1  # consume '['
        out = []
        while True:
            c = self._peek()
            if c == "]":
                self.i += 1
                return out
            if c == "":
                raise ValueError("unterminated array")
            out.append(self._value())


def parse_hocon(text: str) -> dict:
    return _Hocon(text).parse()


def _notary_from(d: dict) -> NotaryConfig:
    raft = bft = None
    if "raft" in d:
        r = d["raft"]
        raft = RaftConfig(
            node_address=r["nodeAddress"],
            cluster_addresses=tuple(r.get("clusterAddresses", [])),
        )
    if "bftSMaRt" in d:
        b = d["bftSMaRt"]
        bft = BFTConfig(
            replica_id=int(b["replicaId"]),
            cluster_addresses=tuple(b.get("clusterAddresses", [])),
            expose_races=bool(b.get("exposeRaces", False)),
        )
    return NotaryConfig(validating=bool(d.get("validating", False)), raft=raft, bft=bft)


def config_from_dict(d: dict) -> NodeConfiguration:
    users = tuple(
        RpcUser(
            u["username"],
            # passwordHash carries a pbkdf2$... entry (hash_rpc_password);
            # check_password dispatches on the prefix, so both land in the
            # same field
            u["passwordHash"] if "passwordHash" in u
            else _plain_password(u["password"]),
            tuple(u.get("permissions", [])),
        )
        for u in d.get("rpcUsers", [])
    )
    return NodeConfiguration(
        my_legal_name=d["myLegalName"],
        base_directory=d.get("baseDirectory", "."),
        p2p_address=d.get("p2pAddress", "localhost:10002"),
        rpc_address=d.get("rpcAddress"),
        notary=_notary_from(d["notary"]) if "notary" in d else None,
        verifier_type=VerifierType(d.get("verifierType", "DeviceBatched")),
        rpc_users=users,
        dev_mode=bool(d.get("devMode", True)),
        network_map_address=d.get("networkMapAddress"),
        message_redelivery_delay_seconds=float(
            d.get("messageRedeliveryDelaySeconds", 30.0)
        ),
        flow_timeout_seconds=float(d.get("flowTimeoutSeconds", 120.0)),
        verification_batch_max=int(d.get("verificationBatchMax", 1024)),
        cordapp_packages=tuple(d.get("cordappPackages", [])),
        cordapp_directory=d.get("cordappDirectory"),
        mesh_fan_out=(
            bool(d["meshFanOut"]) if "meshFanOut" in d else None
        ),
        verification_window_ms=float(d.get("verificationWindowMs", 5.0)),
        database_path=d.get("databasePath"),
    )


def load_config(path: str | Path) -> NodeConfiguration:
    """Load a node.conf (HOCON subset or plain JSON)."""
    text = Path(path).read_text()
    try:
        d = json.loads(text)
    except json.JSONDecodeError:
        d = parse_hocon(text)
    return config_from_dict(d)
