"""Network map: the node directory service.

Parity with the reference's node/.../services/network/ —
``NetworkMapCache`` (local cache of NodeInfos, notary discovery, change
feed) and the registration protocol of ``NetworkMapService``
(NetworkMapService.kt:66-74 fetch/register/subscribe/push topics). The
messaging-protocol variant rides the messaging layer's topics; a
file-based bootstrap (reference: NodeInfoWatcher) is the simple path for
driver/demo setups.
"""

from __future__ import annotations

import dataclasses
import threading

from corda_tpu.ledger import CordaX500Name, Party
from corda_tpu.serialization import deserialize, register_custom, serialize


@dataclasses.dataclass(frozen=True)
class NodeInfo:
    """(reference: core/.../node/NodeInfo.kt — addresses, identities,
    platform version, serial for last-write-wins updates)."""

    addresses: tuple[str, ...]
    legal_identities: tuple[Party, ...]
    platform_version: int = 1
    serial: int = 0
    # "validating" | "simple" | "" — advertised notary service, so peers
    # learn notaries (and their protocol mode) from map registration alone
    notary_mode: str = ""

    @property
    def legal_identity(self) -> Party:
        return self.legal_identities[0]


register_custom(
    NodeInfo, "node.NodeInfo",
    to_fields=lambda n: {
        "addresses": list(n.addresses),
        "identities": list(n.legal_identities),
        "pv": n.platform_version,
        "serial": n.serial,
        "notary_mode": n.notary_mode,
    },
    from_fields=lambda d: NodeInfo(
        tuple(d["addresses"]), tuple(d["identities"]), d["pv"], d["serial"],
        d.get("notary_mode", ""),
    ),
)


class NetworkMapCache:
    """Thread-safe directory cache with a change feed (reference:
    PersistentNetworkMapCache / NetworkMapCache interface)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: dict[CordaX500Name, NodeInfo] = {}
        self._notaries: list[Party] = []
        self._validating_notaries: set = set()  # owning_key set
        self._subscribers: list = []

    def add_node(self, info: NodeInfo) -> None:
        with self._lock:
            name = info.legal_identity.name
            existing = self._nodes.get(name)
            if existing is not None and existing.serial > info.serial:
                return  # stale update (last-write-wins by serial)
            self._nodes[name] = info
            # the notary side effect stays under the lock so the serial
            # last-write-wins check above also orders notary updates — a
            # stale registration must not re-promote a decommissioned notary
            if info.notary_mode:
                self.add_notary(
                    info.legal_identity,
                    validating=(info.notary_mode == "validating"),
                )
            else:
                self._remove_notary(info.legal_identity)
            subs = list(self._subscribers)
        for cb in subs:
            cb("ADD", info)

    def _remove_notary(self, party: Party) -> None:
        with self._lock:
            self._notaries = [
                n for n in self._notaries
                if n.owning_key != party.owning_key
            ]
            self._validating_notaries.discard(party.owning_key)

    def remove_node(self, info: NodeInfo) -> None:
        self._remove_notary(info.legal_identity)
        with self._lock:
            self._nodes.pop(info.legal_identity.name, None)
            subs = list(self._subscribers)
        for cb in subs:
            cb("REMOVE", info)

    def get_node_by_legal_name(self, name: CordaX500Name) -> NodeInfo | None:
        with self._lock:
            return self._nodes.get(name)

    def get_node_by_party(self, party: Party) -> NodeInfo | None:
        with self._lock:
            for info in self._nodes.values():
                if any(p.owning_key == party.owning_key
                       for p in info.legal_identities):
                    return info
        return None

    def all_nodes(self) -> list[NodeInfo]:
        with self._lock:
            return list(self._nodes.values())

    def untrack(self, callback) -> None:
        with self._lock:
            try:
                self._subscribers.remove(callback)
            except ValueError:
                pass

    def track(self, callback) -> list[NodeInfo]:
        with self._lock:
            self._subscribers.append(callback)
            return list(self._nodes.values())

    # -- notaries -------------------------------------------------------------

    def add_notary(self, party: Party, validating: bool = True) -> None:
        with self._lock:
            if all(n.owning_key != party.owning_key for n in self._notaries):
                self._notaries.append(party)
            if validating:
                self._validating_notaries.add(party.owning_key)
            else:
                self._validating_notaries.discard(party.owning_key)

    @property
    def notary_identities(self) -> list[Party]:
        with self._lock:
            return list(self._notaries)

    def get_notary(self, name: CordaX500Name | None = None) -> Party | None:
        with self._lock:
            if name is None:
                return self._notaries[0] if self._notaries else None
            for n in self._notaries:
                if n.name == name:
                    return n
        return None

    def is_notary(self, party: Party) -> bool:
        with self._lock:
            return any(n.owning_key == party.owning_key for n in self._notaries)

    def is_validating_notary(self, party: Party) -> bool:
        """Whether the notary runs the validating protocol — decides what
        the client sends it: the full SignedTransaction (validating) or a
        privacy-preserving tear-off (non-validating). Reference: the service
        type advertised in the network map entry."""
        with self._lock:
            return party.owning_key in self._validating_notaries


class NetworkMapClient:
    """Register with / fetch from a network-map node over messaging topics
    (reference: NetworkMapService fetch/register/subscribe protocol)."""

    TOPIC_REGISTER = "platform.network-map.register"
    TOPIC_FETCH = "platform.network-map.fetch"
    TOPIC_PUSH = "platform.network-map.push"

    def __init__(self, messaging, cache: NetworkMapCache):
        self._messaging = messaging
        self._cache = cache
        messaging.add_handler(self.TOPIC_PUSH, self._on_push)

    def _on_push(self, msg, ack=None) -> None:
        self._cache.add_node(deserialize(msg.payload))
        if ack:
            ack()

    def register(self, map_peer, my_info: NodeInfo) -> None:
        self._messaging.send(map_peer, self.TOPIC_REGISTER, serialize(my_info))


class NetworkMapServer:
    """The map-service side: accept registrations, push updates to all
    subscribers (reference: PersistentNetworkMapService)."""

    def __init__(self, messaging, cache: NetworkMapCache | None = None):
        self._messaging = messaging
        self.cache = cache or NetworkMapCache()
        self._subscribers: set = set()
        self._lock = threading.Lock()
        messaging.add_handler(NetworkMapClient.TOPIC_REGISTER, self._on_register)

    def _on_register(self, msg, ack=None) -> None:
        info = deserialize(msg.payload)
        self.cache.add_node(info)
        with self._lock:
            self._subscribers.add(msg.sender)
            targets = list(self._subscribers)
        # push the full map to the newcomer and the newcomer to everyone
        for node in self.cache.all_nodes():
            self._messaging.send(
                msg.sender, NetworkMapClient.TOPIC_PUSH, serialize(node)
            )
        for peer in targets:
            if peer != msg.sender:
                self._messaging.send(
                    peer, NetworkMapClient.TOPIC_PUSH, serialize(info)
                )
        if ack:
            ack()
