"""ServiceHub: the node's service locator.

Parity with the reference's ``ServiceHub`` (core/.../node/ServiceHub.kt:62-209
— vaultService, keyManagementService, identityService, attachments,
validatedTransactions, networkMapCache, transactionVerifierService, clock,
``loadState``/``toStateAndRef`` resolution, ``signInitialTransaction``,
``recordTransactions``) and ``ServiceHubInternal``
(node/.../services/api/ — + monitoring, scheduler). One concrete class;
every service injectable for the MockServices test tier.
"""

from __future__ import annotations

import time

from corda_tpu.crypto import KeyPair, SecureHash
from corda_tpu.ledger import (
    Party,
    SignedTransaction,
    StateAndRef,
    StateRef,
    TransactionState,
)
from corda_tpu.verifier import InMemoryVerifierService

from .identity import IdentityService, KeyManagementService
from .monitoring import MetricRegistry
from .network_map import NetworkMapCache
from .storage import AttachmentStorage, DBTransactionStorage
from .vault import NodeVaultService


class TransactionResolutionError(Exception):
    def __init__(self, tx_id: SecureHash):
        self.tx_id = tx_id
        super().__init__(f"transaction {tx_id} not found in storage")


class ServiceHub:
    """The service locator handed to flows and contracts."""

    def __init__(
        self,
        my_info=None,
        key_management_service: KeyManagementService | None = None,
        identity_service: IdentityService | None = None,
        vault_service: NodeVaultService | None = None,
        validated_transactions: DBTransactionStorage | None = None,
        attachments: AttachmentStorage | None = None,
        network_map_cache: NetworkMapCache | None = None,
        verifier_service=None,
        metrics: MetricRegistry | None = None,
        clock=time.time,
        notary_service=None,
    ):
        self.my_info = my_info
        self.key_management_service = key_management_service or KeyManagementService()
        self.identity_service = identity_service or IdentityService()
        self.validated_transactions = validated_transactions or DBTransactionStorage()
        self.vault_service = vault_service or NodeVaultService(
            my_keys=self.key_management_service.keys
        )
        self.attachments = attachments or AttachmentStorage()
        self.network_map_cache = network_map_cache or NetworkMapCache()
        self.transaction_verifier_service = verifier_service or InMemoryVerifierService()
        self.metrics = metrics or MetricRegistry()
        self.clock = clock
        self.scheduler_service = None  # wired by the node container
        # the NotaryService this node runs, if it is a notary (reference:
        # AbstractNode.makeCoreNotaryService, AbstractNode.kt:615-632)
        self.notary_service = notary_service
        # commit listeners: the flow engine registers here so a PARKED
        # wait_for_ledger_commit wakes when its transaction records (the
        # reference's equivalent push is DBTransactionStorage.updates
        # feeding waitForLedgerCommit)
        self._commit_listeners: list = []

    def add_commit_listener(self, fn) -> None:
        """``fn(stx)`` fires after each NEWLY-recorded transaction."""
        self._commit_listeners.append(fn)

    # -- identity conveniences ------------------------------------------------

    @property
    def my_identity(self) -> Party | None:
        if self.my_info is None:
            return None
        return self.my_info.legal_identity

    # -- state resolution (reference: ServiceHub.loadState/toStateAndRef) -----

    def load_state(self, ref: StateRef) -> TransactionState:
        stx = self.validated_transactions.get(ref.txhash)
        if stx is None:
            raise TransactionResolutionError(ref.txhash)
        return stx.tx.outputs[ref.index]

    def to_state_and_ref(self, ref: StateRef) -> StateAndRef:
        return StateAndRef(self.load_state(ref), ref)

    # -- recording (reference: ServiceHub.recordTransactions) -----------------

    def record_transactions(self, *stxs: SignedTransaction) -> None:
        """Store validated transactions and feed the vault; idempotent on
        replays (first-write-wins in storage, vault update skipped)."""
        for stx in stxs:
            if self.validated_transactions.add_transaction(stx):
                self.vault_service.record_transaction(stx)
                for fn in list(self._commit_listeners):
                    fn(stx)

    # -- signature verification routing ---------------------------------------

    def verify_stx_signatures(self, stx, allowed_missing=frozenset()) -> None:
        """Signature-set + signer-set validation of one transaction for the
        flow hot path. When this node runs the device-batched verifier
        tier, the check routes through the process-global serving
        scheduler (INTERACTIVE class) so concurrent flows' singleton
        verifies coalesce with verifier/notary batches into one device
        dispatch instead of paying a host loop each. Verdicts match
        ``stx.verify_signatures_except`` exactly (pass/fail per tx);
        invalid signatures surface as the batch tier's
        ``InvalidSignatureError``. Overload or a shut-down scheduler sheds
        to the direct host path.

        Traced as ``flow.verify_stx`` under the calling flow's span
        (docs/OBSERVABILITY.md); the scheduler's queue-wait and batch
        spans hang off it, which is how a slow flow p99 is attributed to
        queue wait vs device time."""
        from corda_tpu.observability import SPAN_FLOW_VERIFY, tracer

        trc = tracer()
        span = trc.start(SPAN_FLOW_VERIFY, trc.current(),
                         attrs={"tx.id": str(stx.id)})
        with span, trc.activate(span):
            allowed = set(allowed_missing)
            svc = self.transaction_verifier_service
            if getattr(svc, "routes_via_scheduler", False):
                from concurrent.futures import TimeoutError as _FutTimeout

                from corda_tpu.serving import (
                    INTERACTIVE,
                    ServingError,
                    device_scheduler,
                )

                try:
                    report = device_scheduler().submit_transactions(
                        [stx], [allowed], priority=INTERACTIVE,
                        use_device=getattr(svc, "use_device", False),
                    ).result(timeout=120)
                except (ServingError, _FutTimeout):
                    # explicit shed (admission reject / shutdown race) or a
                    # wedged scheduler: the flow must not fail on overload —
                    # fall through to the direct host check (idempotent)
                    span.set_attr("degraded", "host-fallback")
                else:
                    report.raise_first()
                    return
            from corda_tpu.observability.flowprof import flowprof_frame

            with flowprof_frame("host_verify"):
                stx.verify_signatures_except(allowed)

    # -- signing (reference: ServiceHub.signInitialTransaction :187-209) ------

    def _keypair_for(self, public_key=None) -> KeyPair:
        kms = self.key_management_service
        if public_key is None:
            if self.my_identity is not None:
                public_key = self.my_identity.owning_key
            else:
                public_key = next(iter(kms.keys))
        return kms._require(public_key)

    def sign_initial_transaction(self, builder, public_key=None) -> SignedTransaction:
        return builder.sign_initial_transaction(self._keypair_for(public_key))

    def add_signature(self, stx: SignedTransaction, public_key=None) -> SignedTransaction:
        key = public_key or self.my_identity.owning_key
        sig = self.key_management_service.sign(stx.id, key)
        return stx.with_additional_signature(sig)

    # -- ledger-tx resolution for verification --------------------------------

    def resolve_to_ledger_transaction(self, stx: SignedTransaction):
        return stx.tx.to_ledger_transaction(self.load_state)

    def shutdown(self) -> None:
        self.transaction_verifier_service.shutdown()
        self.validated_transactions.close()
        self.vault_service.close()
        self.attachments.close()
