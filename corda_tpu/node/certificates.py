"""Node certificate provisioning for the secure fabric.

Role parity with the reference's certificate story: every node owns an
identity certificate chaining to the network trust root, stored under
``<base>/certificates`` (reference: node/.../utilities/X509Utilities.kt +
KeyStoreUtilities.kt — keystores created by ``initCertificate``,
AbstractNode.kt:204), and in dev mode the certificates are auto-issued
from a WELL-KNOWN dev CA whose private key ships with the platform
(reference: the published dev certificates behind ``devMode``,
NodeConfiguration.kt:25 — explicitly not a secret, exactly like here).

Production mode (``dev_mode = false``) refuses to auto-provision: the
operator must place ``identity.cbe`` and ``truststore.cbe`` (issued by the
real network operator's root) in the certificates directory.
"""

from __future__ import annotations

import dataclasses
import hashlib
from pathlib import Path

from corda_tpu.crypto import KeyPair, PublicKey, derive_keypair_from_entropy
from corda_tpu.ledger import CordaX500Name, Party
from corda_tpu.ledger.identity import NameKeyCertificate, PartyAndCertificate
from corda_tpu.serialization import deserialize, serialize

# The dev-mode network root: deterministic, public, NOT a secret — any peer
# accepting it accepts dev-tier security, the same trust model as the
# reference's checked-in dev CA keystores.
_DEV_ROOT_ENTROPY = hashlib.sha256(b"corda-tpu dev network root CA v1").digest()


def dev_trust_root() -> KeyPair:
    from corda_tpu.crypto.schemes import EDDSA_ED25519_SHA512

    return derive_keypair_from_entropy(EDDSA_ED25519_SHA512, _DEV_ROOT_ENTROPY)


@dataclasses.dataclass(frozen=True)
class NodeIdentity:
    """A node's fabric credentials: certified identity + signing key +
    the trust root it (and every peer it accepts) chains to."""

    certificate: PartyAndCertificate
    keypair: KeyPair
    trust_root: PublicKey

    @property
    def party(self) -> Party:
        return self.certificate.party


def issue_identity(
    name: CordaX500Name | str, keypair: KeyPair, ca: KeyPair | None = None
) -> NodeIdentity:
    """Issue a root-signed identity certificate (dev CA by default)."""
    if isinstance(name, str):
        name = CordaX500Name.parse(name)
    ca = ca or dev_trust_root()
    leaf = NameKeyCertificate.issue(name, keypair.public, ca.public, ca.private)
    cert = PartyAndCertificate(Party(name, keypair.public), (leaf,))
    return NodeIdentity(cert, keypair, ca.public)


def save_identity(cert_dir: str | Path, ident: NodeIdentity) -> None:
    d = Path(cert_dir)
    d.mkdir(parents=True, exist_ok=True)
    (d / "identity.cbe").write_bytes(serialize({
        "certificate": ident.certificate,
        "public": ident.keypair.public,
        "private": ident.keypair.private,
    }))
    (d / "truststore.cbe").write_bytes(serialize({"root": ident.trust_root}))


def load_identity(cert_dir: str | Path) -> NodeIdentity:
    d = Path(cert_dir)
    ident = deserialize((d / "identity.cbe").read_bytes())
    trust = deserialize((d / "truststore.cbe").read_bytes())
    ni = NodeIdentity(
        ident["certificate"],
        KeyPair(ident["public"], ident["private"]),
        trust["root"],
    )
    if not ni.certificate.verify(ni.trust_root):
        raise ValueError(
            f"{d}/identity.cbe does not chain to {d}/truststore.cbe"
        )
    return ni


def node_certificates(
    base_directory: str | Path, legal_name: str, *, dev_mode: bool = True,
    keypair: KeyPair | None = None,
) -> NodeIdentity:
    """Load ``<base>/certificates``, or in dev mode provision it from the
    dev CA (reference: initCertificate under devMode, AbstractNode.kt:204).
    The issued keypair persists, so a restarted node keeps its identity."""
    cert_dir = Path(base_directory) / "certificates"
    if (cert_dir / "identity.cbe").exists():
        ident = load_identity(cert_dir)
        expected = CordaX500Name.parse(str(legal_name))
        if ident.party.name != expected:
            raise ValueError(
                f"certificates at {cert_dir} are for {ident.party.name}, "
                f"node is {expected}"
            )
        return ident
    if not dev_mode:
        raise FileNotFoundError(
            f"no identity at {cert_dir} and devMode is off — provision "
            "identity.cbe/truststore.cbe from the network operator"
        )
    from corda_tpu.crypto import generate_keypair

    ident = issue_identity(legal_name, keypair or generate_keypair())
    save_identity(cert_dir, ident)
    return ident
