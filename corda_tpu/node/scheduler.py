"""Scheduler: time-triggered state activities.

Parity with the reference's node/.../services/events/
``NodeSchedulerService`` (NodeSchedulerService.kt:55-170 — earliest-due
scheduled state wins; rescheduled on vault changes) and
``ScheduledActivityObserver`` (watches vault updates for
``SchedulableState`` outputs). Virtual-clock friendly: inject a clock and
call ``pump()`` for deterministic tests (the reference's TestClock idiom).
"""

from __future__ import annotations

import dataclasses
import heapq
import threading
import time
from typing import Protocol, runtime_checkable

from corda_tpu.ledger import StateRef


@runtime_checkable
class SchedulableState(Protocol):
    """(reference: core SchedulableState.nextScheduledActivity)."""

    def next_scheduled_activity(self, ref: StateRef) -> "ScheduledActivity | None":
        ...


@dataclasses.dataclass(frozen=True, order=True)
class ScheduledActivity:
    """A flow to launch at a time (reference: ScheduledActivity — here the
    flow is named by class path + args so it survives restarts)."""

    scheduled_at: float  # unix seconds
    flow_class_path: str = dataclasses.field(compare=False)
    flow_args: tuple = dataclasses.field(default=(), compare=False)


def make_scheduled_flow_starter(smm, party_name):
    """The start-callable a scheduler drives: load the flow class, start
    it, and LOG failures — nothing awaits a scheduler-started flow's
    future, so without the callback an error would vanish silently.
    Shared by the production node container and the mocknet tier."""
    import logging

    from corda_tpu.flows.api import load_class

    logger = logging.getLogger(__name__)

    def start(flow_class_path: str, args):
        handle = smm.start_flow(load_class(flow_class_path)(*args))

        def _report(fut):
            if fut.cancelled():
                return  # node shutdown cancels in-flight flows
            exc = fut.exception()
            if exc is not None:
                logger.error(
                    "%s: scheduled flow %s%r failed: %r",
                    party_name, flow_class_path, tuple(args), exc,
                )

        handle.result.add_done_callback(_report)
        return handle

    return start


class NodeSchedulerService:
    """Earliest-deadline scheduler over SchedulableState outputs."""

    def __init__(self, start_flow, clock=time.time):
        self._start_flow = start_flow  # callable(flow_class_path, args)
        self._clock = clock
        self._lock = threading.Lock()
        self._heap: list[tuple[float, str, ScheduledActivity, StateRef]] = []
        # pending-entry count per key; a cancel only registers when the
        # key still has live heap entries (else the tombstone would leak
        # one set entry per consumed state for the node's lifetime)
        self._pending: dict[str, int] = {}
        self._cancelled: set[str] = set()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def schedule_state_activity(self, ref: StateRef, activity: ScheduledActivity) -> None:
        with self._lock:
            key = str(ref)
            self._cancelled.discard(key)
            self._pending[key] = self._pending.get(key, 0) + 1
            heapq.heappush(self._heap, (activity.scheduled_at, key, activity, ref))

    def unschedule_state_activity(self, ref: StateRef) -> None:
        with self._lock:
            key = str(ref)
            if self._pending.get(key):
                self._cancelled.add(key)

    def observe_vault(self, vault) -> None:
        """Wire to a vault update feed (reference:
        ScheduledActivityObserver): produced SchedulableStates get
        scheduled; consumed ones unscheduled. The subscription snapshot
        re-derives schedules for states already in the vault — a restarted
        node must fire activities its previous life recorded (reference:
        NodeSchedulerService.start's relaxed re-scan on boot)."""

        def on_update(update):
            for sr in update.consumed:
                self.unschedule_state_activity(sr.ref)
            for sr in update.produced:
                self._maybe_schedule(sr)

        snapshot = vault.track(on_update)
        for sr in getattr(snapshot, "states", ()):
            self._maybe_schedule(sr)

    def _maybe_schedule(self, sr) -> None:
        data = sr.state.data
        if isinstance(data, SchedulableState):
            activity = data.next_scheduled_activity(sr.ref)
            if activity is not None:
                self.schedule_state_activity(sr.ref, activity)

    def pump(self) -> int:
        """Run every activity due now; returns how many fired (deterministic
        test path — production uses start())."""
        fired = 0
        now = self._clock()
        while True:
            with self._lock:
                if not self._heap or self._heap[0][0] > now:
                    return fired
                _, key, activity, ref = heapq.heappop(self._heap)
                n = self._pending.get(key, 1) - 1
                if n > 0:
                    self._pending[key] = n
                else:
                    self._pending.pop(key, None)
                if key in self._cancelled:
                    if n <= 0:
                        self._cancelled.discard(key)
                    continue
            try:
                self._start_flow(activity.flow_class_path, activity.flow_args)
                fired += 1
            except Exception:
                # a bad flow path / mismatched args (cordapp bug, version
                # skew) must cost ONE activity, not the scheduler thread —
                # an escaped exception here would kill the loop and
                # silently stop every future activity on the node. Failed
                # starts do NOT count toward `fired` (callers pump until
                # n activities fire — overcounting would end them early
                # while the activity was actually lost).
                import logging

                logging.getLogger(__name__).exception(
                    "failed to start scheduled flow %s%r",
                    activity.flow_class_path, tuple(activity.flow_args),
                )

    def start(self, poll_s: float = 0.05) -> None:
        def loop():
            while not self._stop.wait(poll_s):
                self.pump()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="scheduler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
