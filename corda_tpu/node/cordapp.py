"""CorDapp discovery and loading.

Capability parity with the reference's CordappLoader
(node/.../internal/cordapp/CordappLoader.kt:41-63 — scan the node's
``plugins`` directory for JARs, classpath-scan each for contracts,
initiated flows, RPC-startable flows, schemas and services, and record a
``Cordapp`` manifest per JAR). A JAR here is a Python module or package
dropped in the node's ``cordapps`` directory (or named in config):
importing it registers its pieces, and the loader DIFFS the platform
registries around each import to attribute what the app provides —
jar-scanning re-designed around Python's import system instead of
bytecode scanning.
"""

from __future__ import annotations

import dataclasses
import importlib
import importlib.util
import logging
import sys
from pathlib import Path

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Cordapp:
    """What one app module provides (reference: Cordapp.kt — the manifest
    CordappProviderImpl serves)."""

    name: str
    module: str
    contracts: tuple[str, ...]        # registered contract identifiers
    initiated_flows: tuple[str, ...]  # initiating-flow names with responders
    flow_classes: tuple[str, ...]     # FlowLogic classes defined by the app
    serializable_types: tuple[str, ...]


# app-provided node services: (services-hub attribute name, class).
# Populated at cordapp import time by @CordaService; instantiated per node
# by install_corda_services (reference: @CordaService classes found by the
# cordapp scan and built in AbstractNode.installCordaServices).
_CORDA_SERVICES: list[tuple[str, type]] = []


def CordaService(attr_name: str):
    """Register the decorated class as a node service: every node that
    loads the defining cordapp instantiates it at boot as
    ``services.<attr_name>`` with ``cls(services, party, keypair)``
    (reference: @CordaService + AbstractNode.installCordaServices — the
    oracle-in-a-node pattern, NodeInterestRates.kt:79)."""

    def deco(cls):
        # idempotent AND current: importlib.reload re-runs the decorator
        # with a new class under the SAME module path — that REPLACES
        # the entry so nodes booted after a reload instantiate the
        # reloaded class, not the stale one. The same source file
        # imported under TWO package paths keeps one entry per path
        # (each node's loaded_modules filter must match its own path);
        # install_corda_services recognizes such same-source duplicates
        # at install time instead of mislabelling them collisions.
        for i, (a, c) in enumerate(_CORDA_SERVICES):
            if (
                a == attr_name
                and c.__qualname__ == cls.__qualname__
                and c.__module__ == cls.__module__
            ):
                _CORDA_SERVICES[i] = (attr_name, cls)
                break
        else:
            _CORDA_SERVICES.append((attr_name, cls))
        cls._corda_service_attr = attr_name
        return cls

    return deco


def _same_service_source(a, b) -> bool:
    """Two registry classes that are really one service: same qualname
    and same defining source file (the two-package-path import shape)."""
    if a.__qualname__ != b.__qualname__:
        return False
    import inspect

    try:
        return inspect.getfile(a) == inspect.getfile(b)
    except Exception:
        return False


def install_corda_services(services, party, keypair,
                           loaded_modules=None) -> list[str]:
    """Instantiate registered cordapp services onto a node's ServiceHub.
    ``loaded_modules`` restricts installation to services whose defining
    module is among THIS node's loaded cordapps — the registry is
    process-global, and in multi-node processes (mocknet, tests) a node
    that never loaded the defining app must not acquire its services
    (e.g. an oracle signing under the wrong node's identity). One broken
    service must not stop the boot (mirrors the loader's skip-on-error
    policy)."""
    installed = []
    for attr, cls in _CORDA_SERVICES:
        if loaded_modules is not None and not any(
            cls.__module__ == m or cls.__module__.startswith(m + ".")
            for m in loaded_modules
        ):
            # defined by a cordapp this node did not load (package match
            # includes submodules: myapp/oracle.py belongs to app "myapp")
            continue
        if hasattr(services, attr):
            existing = getattr(services, attr)
            if _same_service_source(type(existing), cls):
                # the same service registered under two import paths —
                # already installed on this hub; benign, not a collision
                continue
            # never let an app shadow a core hub service ("vault_service",
            # "metrics", …) — the node would run with a cordapp object
            # where the vault should be and fail far from the cause
            logger.error(
                "refusing to install corda service %r from %s: the name "
                "collides with an existing ServiceHub attribute",
                attr, cls.__module__,
            )
            continue
        try:
            setattr(services, attr, cls(services, party, keypair))
            installed.append(attr)
        except Exception:
            logger.exception("failed to install corda service %r", attr)
    return installed


def _registry_snapshot():
    from corda_tpu.flows.api import _RESPONDERS
    from corda_tpu.ledger.states import _CONTRACT_REGISTRY
    from corda_tpu.serialization.cbe import _REGISTRY

    return (
        set(_CONTRACT_REGISTRY),
        set(_RESPONDERS),
        set(_REGISTRY),
    )


def _flow_classes_of(module) -> tuple[str, ...]:
    import inspect

    from corda_tpu.flows.api import FlowLogic

    out = []
    for name, obj in inspect.getmembers(module, inspect.isclass):
        if (issubclass(obj, FlowLogic) and obj is not FlowLogic
                and obj.__module__ == module.__name__):
            out.append(f"{obj.__module__}.{name}")
    return tuple(sorted(out))


class CordappLoader:
    """Loads apps and records a manifest per app (reference:
    CordappLoader.createDefault + CordappProviderImpl)."""

    def __init__(self):
        self.cordapps: list[Cordapp] = []

    def load_package(self, package: str) -> Cordapp:
        """Import one app package/module and attribute its registrations."""
        before = _registry_snapshot()
        module = importlib.import_module(package)
        after = _registry_snapshot()
        app = Cordapp(
            name=package.rpartition(".")[2] or package,
            module=package,
            contracts=tuple(sorted(after[0] - before[0])),
            initiated_flows=tuple(sorted(after[1] - before[1])),
            flow_classes=_flow_classes_of(module),
            serializable_types=tuple(sorted(after[2] - before[2])),
        )
        self.cordapps.append(app)
        return app

    def load_directory(self, directory: str | Path) -> list[Cordapp]:
        """Scan a ``cordapps`` directory (the reference's ``plugins`` dir
        scan, CordappLoader.getCordappsInDirectory): every ``*.py`` file
        and every package (directory with ``__init__.py``) is an app."""
        directory = Path(directory)
        if not directory.is_dir():
            return []
        loaded = []
        entries = sorted(directory.iterdir(), key=lambda p: p.name)
        if str(directory) not in sys.path:
            sys.path.insert(0, str(directory))
        for entry in entries:
            name = None
            if entry.suffix == ".py" and not entry.name.startswith("_"):
                name = entry.stem
            elif entry.is_dir() and (entry / "__init__.py").exists():
                name = entry.name
            if name is None:
                continue
            try:
                loaded.append(self.load_package(name))
            except Exception:
                # one broken app must not stop the node boot; mirrors the
                # reference logging and skipping unscannable jars
                logger.exception("failed to load cordapp %r", name)
        return loaded

    # ------------------------------------------------------ provider face
    def contract_attachment_id(self, contract_name: str):
        """The app 'attachment' backing a contract (reference:
        CordappProviderImpl.getContractAttachmentID)."""
        from corda_tpu.ledger.states import contract_code_hash

        for app in self.cordapps:
            if contract_name in app.contracts:
                return contract_code_hash(contract_name)
        return None

    def cordapp_for_contract(self, contract_name: str) -> Cordapp | None:
        for app in self.cordapps:
            if contract_name in app.contracts:
                return app
        return None
