"""CorDapp discovery and loading.

Capability parity with the reference's CordappLoader
(node/.../internal/cordapp/CordappLoader.kt:41-63 — scan the node's
``plugins`` directory for JARs, classpath-scan each for contracts,
initiated flows, RPC-startable flows, schemas and services, and record a
``Cordapp`` manifest per JAR). A JAR here is a Python module or package
dropped in the node's ``cordapps`` directory (or named in config):
importing it registers its pieces, and the loader DIFFS the platform
registries around each import to attribute what the app provides —
jar-scanning re-designed around Python's import system instead of
bytecode scanning.
"""

from __future__ import annotations

import dataclasses
import importlib
import importlib.util
import logging
import sys
from pathlib import Path

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Cordapp:
    """What one app module provides (reference: Cordapp.kt — the manifest
    CordappProviderImpl serves)."""

    name: str
    module: str
    contracts: tuple[str, ...]        # registered contract identifiers
    initiated_flows: tuple[str, ...]  # initiating-flow names with responders
    flow_classes: tuple[str, ...]     # FlowLogic classes defined by the app
    serializable_types: tuple[str, ...]


def _registry_snapshot():
    from corda_tpu.flows.api import _RESPONDERS
    from corda_tpu.ledger.states import _CONTRACT_REGISTRY
    from corda_tpu.serialization.cbe import _REGISTRY

    return (
        set(_CONTRACT_REGISTRY),
        set(_RESPONDERS),
        set(_REGISTRY),
    )


def _flow_classes_of(module) -> tuple[str, ...]:
    import inspect

    from corda_tpu.flows.api import FlowLogic

    out = []
    for name, obj in inspect.getmembers(module, inspect.isclass):
        if (issubclass(obj, FlowLogic) and obj is not FlowLogic
                and obj.__module__ == module.__name__):
            out.append(f"{obj.__module__}.{name}")
    return tuple(sorted(out))


class CordappLoader:
    """Loads apps and records a manifest per app (reference:
    CordappLoader.createDefault + CordappProviderImpl)."""

    def __init__(self):
        self.cordapps: list[Cordapp] = []

    def load_package(self, package: str) -> Cordapp:
        """Import one app package/module and attribute its registrations."""
        before = _registry_snapshot()
        module = importlib.import_module(package)
        after = _registry_snapshot()
        app = Cordapp(
            name=package.rpartition(".")[2] or package,
            module=package,
            contracts=tuple(sorted(after[0] - before[0])),
            initiated_flows=tuple(sorted(after[1] - before[1])),
            flow_classes=_flow_classes_of(module),
            serializable_types=tuple(sorted(after[2] - before[2])),
        )
        self.cordapps.append(app)
        return app

    def load_directory(self, directory: str | Path) -> list[Cordapp]:
        """Scan a ``cordapps`` directory (the reference's ``plugins`` dir
        scan, CordappLoader.getCordappsInDirectory): every ``*.py`` file
        and every package (directory with ``__init__.py``) is an app."""
        directory = Path(directory)
        if not directory.is_dir():
            return []
        loaded = []
        entries = sorted(directory.iterdir(), key=lambda p: p.name)
        if str(directory) not in sys.path:
            sys.path.insert(0, str(directory))
        for entry in entries:
            name = None
            if entry.suffix == ".py" and not entry.name.startswith("_"):
                name = entry.stem
            elif entry.is_dir() and (entry / "__init__.py").exists():
                name = entry.name
            if name is None:
                continue
            try:
                loaded.append(self.load_package(name))
            except Exception:
                # one broken app must not stop the node boot; mirrors the
                # reference logging and skipping unscannable jars
                logger.exception("failed to load cordapp %r", name)
        return loaded

    # ------------------------------------------------------ provider face
    def contract_attachment_id(self, contract_name: str):
        """The app 'attachment' backing a contract (reference:
        CordappProviderImpl.getContractAttachmentID)."""
        from corda_tpu.ledger.states import contract_code_hash

        for app in self.cordapps:
            if contract_name in app.contracts:
                return contract_code_hash(contract_name)
        return None

    def cordapp_for_contract(self, contract_name: str) -> Cordapp | None:
        for app in self.cordapps:
            if contract_name in app.contracts:
                return app
        return None
