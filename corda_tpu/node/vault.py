"""The vault: the node's view of states it cares about.

Parity with the reference's node/.../services/vault/ —
``NodeVaultService`` (tracks unconsumed/consumed states from recorded
transactions, emits ``Vault.Update``s), the query engine
(``HibernateQueryCriteriaParser`` criteria → SQL; here criteria → SQLite
over an indexed state table), and ``VaultSoftLockManager`` (flow-scoped
soft locks so concurrent spenders don't select the same coins).

Schema: one row per output state (tx, index, contract, state class,
notary, participants, consumed flag, soft-lock id, fungible quantity +
token for coin selection), with the state object itself CBE-serialized.
"""

from __future__ import annotations

import dataclasses
import enum
import sqlite3
import threading

from corda_tpu.crypto import SecureHash
from corda_tpu.ledger import SignedTransaction, StateAndRef, StateRef, TransactionState
from corda_tpu.ledger.states import Amount
from corda_tpu.serialization import deserialize, register_custom, serialize


class StateStatus(enum.Enum):
    UNCONSUMED = "UNCONSUMED"
    CONSUMED = "CONSUMED"
    ALL = "ALL"


@dataclasses.dataclass(frozen=True)
class PageSpecification:
    """(reference: PageSpecification in vault/QueryCriteria.kt —
    1-based page numbers)."""

    page_number: int = 1
    page_size: int = 200


@dataclasses.dataclass(frozen=True)
class Sort:
    """Sort by a recognised column (reference: Sort/SortAttribute)."""

    by: str = "recorded"  # recorded | contract | quantity
    descending: bool = False

    _COLUMNS = {"recorded": "rowid", "contract": "contract", "quantity": "quantity"}


@dataclasses.dataclass(frozen=True)
class QueryCriteria:
    """Composable vault query criteria (reference: QueryCriteria.kt —
    VaultQueryCriteria + FungibleAssetQueryCriteria folded into one
    dataclass; ``and_``/``or_`` composition is replaced by explicit field
    conjunction, the dominant real-world use)."""

    status: StateStatus = StateStatus.UNCONSUMED
    contract_state_types: tuple[type, ...] | None = None
    state_refs: tuple[StateRef, ...] | None = None
    notary_names: tuple[str, ...] | None = None
    participant_keys: tuple | None = None  # PublicKey
    include_soft_locked: bool = True
    soft_lock_id: str | None = None  # states locked by this flow also visible
    quantity_geq: int | None = None  # fungible: quantity >= (coin selection)
    token_repr: str | None = None  # fungible: exact token match


class SoftLockError(Exception):
    pass


@dataclasses.dataclass(frozen=True)
class VaultUpdate:
    """(reference: Vault.Update — consumed/produced sets per tx)."""

    consumed: tuple[StateAndRef, ...]
    produced: tuple[StateAndRef, ...]

    @property
    def is_empty(self) -> bool:
        return not self.consumed and not self.produced


@dataclasses.dataclass(frozen=True)
class Page:
    """(reference: Vault.Page — results + total count for paging UIs)."""

    states: list[StateAndRef]
    total_states_available: int


class Vault:
    """Namespace mirror of the reference's ``Vault`` container class."""

    StateStatus = StateStatus
    Update = VaultUpdate
    Page = Page


def _token_repr(token) -> str:
    return repr(token)


class NodeVaultService:
    """SQLite-backed vault (reference: NodeVaultService.kt).

    Relevancy: a produced output is recorded iff the node's keys intersect
    its participants (or ``observe_all`` is set — observer-node mode).
    """

    def __init__(self, path: str = ":memory:", my_keys=None, observe_all=False,
                 journal=None, state_index=None):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS vault_states ("
            " tx_id BLOB NOT NULL, output_index INTEGER NOT NULL,"
            " contract TEXT NOT NULL, state_class TEXT NOT NULL,"
            " notary_name TEXT NOT NULL, state_blob BLOB NOT NULL,"
            " consumed INTEGER NOT NULL DEFAULT 0,"
            " consumed_by BLOB, lock_id TEXT,"
            " quantity INTEGER, token TEXT,"
            " PRIMARY KEY (tx_id, output_index))"
        )
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS vault_participants ("
            " tx_id BLOB NOT NULL, output_index INTEGER NOT NULL,"
            " participant_key BLOB NOT NULL)"
        )
        self._db.execute(
            "CREATE INDEX IF NOT EXISTS idx_vault_unconsumed"
            " ON vault_states (consumed, contract)"
        )
        self._db.execute(
            "CREATE INDEX IF NOT EXISTS idx_vault_parts"
            " ON vault_participants (participant_key)"
        )
        self._db.commit()
        self._lock = threading.RLock()
        self._my_keys = set(my_keys or [])
        self._observe_all = observe_all
        self._subscribers: list = []
        # crash-consistent journal (docs/DURABILITY.md): every recorded
        # transaction lands in the durability WAL and is group-commit
        # fsynced BEFORE the vault update reaches any subscriber; recovery
        # rebuilds the consumed/unconsumed pages (newest snapshot + stx
        # replay), which feeds the normal query/track snapshot path — the
        # same Page the RPC monitor's accumulate_feed(seed=) consumes.
        # Meant for the default ':memory:' backing store; a file-backed
        # SQLite vault is already durable on its own.
        self._journal = journal
        self.last_recovery = None
        # device-resident unconsumed-state index (docs/STATE_STORE.md):
        # explicit injection wins; otherwise constructed iff
        # CORDA_TPU_STATESTORE=1 (maybe_vault_index returns None while
        # the feature is off — no device allocations on the default
        # path). Attached BEFORE journal recovery so replay repopulates
        # it alongside the SQL pages.
        if state_index is None:
            from corda_tpu.statestore import maybe_vault_index

            state_index = maybe_vault_index()
        self._state_index = state_index
        # LSN of the last journal record whose SQL effect is known
        # applied (appends happen strictly AFTER their _apply_stx, so a
        # snapshot claiming coverage of this LSN can never lack it)
        self._journal_lsn = -1
        if journal is not None:
            self.last_recovery = journal.recover(
                self._apply_journal, self._load_pages
            )
            self._journal_lsn = journal.wal.durable_lsn
        if self._state_index is not None:
            # converge the device index with whatever SQL already holds:
            # snapshot-restored pages (_load_pages writes SQL directly)
            # and pre-existing rows of a file-backed vault are invisible
            # to stx replay, so without this pass unconsumed_ref_exists
            # would answer a confident False for live states
            self._rebuild_state_index()

    # -- recording ------------------------------------------------------------

    def add_my_key(self, key) -> None:
        with self._lock:
            self._my_keys.add(key)

    def _is_relevant(self, state: TransactionState) -> bool:
        if self._observe_all or not self._my_keys:
            return True
        participants = getattr(state.data, "participants", ())
        for p in participants:
            key = getattr(p, "owning_key", p)
            if key in self._my_keys:
                return True
        return False

    def record_transaction(self, stx: SignedTransaction) -> VaultUpdate:
        """Consume inputs we track, record relevant outputs, emit an update
        (reference: NodeVaultService.notifyAll). With a journal, the WAL
        record is durable before any subscriber sees the update."""
        update, subs, lsn = self._apply_stx(stx, journal=True)
        if self._journal is not None:
            # the group-commit fsync (and the ack it gates — returning,
            # and the subscriber callbacks below) stays OUTSIDE the lock
            self._journal.flush()
            if self._journal.snapshot_due():
                # cover only OUR record: a rival recorder's later append
                # may not be in the dump yet; its record replays
                # idempotently over the snapshot instead
                self._journal.snapshot(self._dump_pages(), covered_lsn=lsn)
        if not update.is_empty:
            for cb in subs:
                cb(update)
        return update

    def _apply_stx(self, stx: SignedTransaction, journal: bool = False):
        """The SQL half of recording one transaction — idempotent (replay
        of an already-recorded stx changes nothing), shared by the live
        path (``journal=True``: the WAL record is appended INSIDE the
        same locked region as the SQL, so WAL order can never invert
        apply order — a spend journaled before its issue would replay
        into an unconsumed spent state) and journal recovery (which must
        not re-append)."""
        wtx = stx.tx
        produced: list[StateAndRef] = []
        consumed: list[StateAndRef] = []
        fresh_adds: list[tuple] = []   # (ref, owner) of NEWLY-inserted rows
        with self._lock:
            for ref in wtx.inputs:
                row = self._db.execute(
                    "SELECT state_blob FROM vault_states"
                    " WHERE tx_id=? AND output_index=? AND consumed=0",
                    (ref.txhash.bytes, ref.index),
                ).fetchone()
                if row is not None:
                    self._db.execute(
                        "UPDATE vault_states SET consumed=1, consumed_by=?, lock_id=NULL"
                        " WHERE tx_id=? AND output_index=?",
                        (stx.id.bytes, ref.txhash.bytes, ref.index),
                    )
                    consumed.append(StateAndRef(deserialize(row[0]), ref))
            for idx, tstate in enumerate(wtx.outputs):
                if not self._is_relevant(tstate):
                    continue
                ref = StateRef(stx.id, idx)
                amount = getattr(tstate.data, "amount", None)
                quantity = token = None
                if isinstance(amount, Amount):
                    quantity, token = amount.quantity, _token_repr(amount.token)
                cur = self._db.execute(
                    "INSERT OR IGNORE INTO vault_states"
                    " (tx_id, output_index, contract, state_class, notary_name,"
                    "  state_blob, quantity, token)"
                    " VALUES (?,?,?,?,?,?,?,?)",
                    (
                        stx.id.bytes, idx, tstate.contract,
                        type(tstate.data).__name__, str(tstate.notary.name),
                        serialize(tstate), quantity, token,
                    ),
                )
                if cur.rowcount == 1:
                    # participants only for a NEWLY-inserted state row, so
                    # an idempotent re-record (journal replay, client
                    # retry) cannot duplicate participant rows
                    for p in getattr(tstate.data, "participants", ()):
                        key = getattr(p, "owning_key", p)
                        self._db.execute(
                            "INSERT INTO vault_participants VALUES (?,?,?)",
                            (stx.id.bytes, idx, serialize(key)),
                        )
                    parts = getattr(tstate.data, "participants", ())
                    owner = (
                        getattr(parts[0], "owning_key", parts[0])
                        if parts else None
                    )
                    fresh_adds.append((ref, owner))
                produced.append(StateAndRef(tstate, ref))
            self._db.commit()
            if self._state_index is not None and (wtx.inputs or fresh_adds):
                # keep the device index synchronous with the SQL pages
                # (same locked region, so a query between the two views
                # can never observe them disagreeing). Removals cover ALL
                # inputs, not just the rows SQL still saw as consumed=0:
                # a replay over an already-applied file-backed vault finds
                # no unconsumed row, yet the index must still converge to
                # "consumed" (removing an absent ref is a no-op). Adds
                # cover only rows whose INSERT landed — a re-offered ref
                # may already be consumed=1 in SQL and must not resurrect
                # in the index.
                self._state_index.remove_states(list(wtx.inputs))
                self._state_index.add_states(fresh_adds)
            lsn = None
            if journal and self._journal is not None:
                lsn = self._journal.append(
                    {"k": "stx", "blob": serialize(stx)}
                )
                self._journal_lsn = max(self._journal_lsn, lsn)
            subs = list(self._subscribers)
        return VaultUpdate(tuple(consumed), tuple(produced)), subs, lsn

    # -- durability journal (docs/DURABILITY.md) -------------------------------

    def _apply_journal(self, rec: dict) -> None:
        if rec["k"] == "stx":
            self._apply_stx(deserialize(rec["blob"]))

    def _dump_pages(self) -> dict:
        """Full-page snapshot payload: raw rows of both vault tables."""
        with self._lock:
            states = self._db.execute(
                "SELECT tx_id, output_index, contract, state_class,"
                " notary_name, state_blob, consumed, consumed_by, lock_id,"
                " quantity, token FROM vault_states ORDER BY tx_id,"
                " output_index"
            ).fetchall()
            parts = self._db.execute(
                "SELECT tx_id, output_index, participant_key"
                " FROM vault_participants ORDER BY tx_id, output_index"
            ).fetchall()
        return {"states": [list(r) for r in states],
                "parts": [list(r) for r in parts]}

    def _load_pages(self, snap: dict) -> None:
        with self._lock:
            fresh: set[tuple] = set()
            for r in snap["states"]:
                cur = self._db.execute(
                    "INSERT OR IGNORE INTO vault_states"
                    " (tx_id, output_index, contract, state_class,"
                    "  notary_name, state_blob, consumed, consumed_by,"
                    "  lock_id, quantity, token)"
                    " VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                    tuple(r),
                )
                if cur.rowcount == 1:
                    fresh.add((bytes(r[0]), r[1]))
            # participants only for state rows this load actually added:
            # a file-backed vault restarting with the journal enabled
            # already holds them, and vault_participants has no unique
            # key to dedupe on — a plain re-insert would duplicate the
            # table on every restart
            self._db.executemany(
                "INSERT INTO vault_participants VALUES (?,?,?)",
                [
                    tuple(r) for r in snap["parts"]
                    if (bytes(r[0]), r[1]) in fresh
                ],
            )
            self._db.commit()

    def _rebuild_state_index(self) -> None:
        """Bulk-load every UNCONSUMED SQL row into the device index
        (idempotent — present rows are re-offered and skipped)."""
        with self._lock:
            rows = self._db.execute(
                "SELECT tx_id, output_index, state_blob FROM vault_states"
                " WHERE consumed=0"
            ).fetchall()
            if not rows:
                return
            adds = []
            for tx_id, idx, blob in rows:
                tstate = deserialize(blob)
                parts = getattr(tstate.data, "participants", ())
                owner = (
                    getattr(parts[0], "owning_key", parts[0])
                    if parts else None
                )
                adds.append((StateRef(SecureHash(tx_id), idx), owner))
            self._state_index.add_states(adds)

    def pages_digest(self) -> str:
        """One hash over the consumed/unconsumed pages (soft-lock ids
        excluded — they are flow-lifetime scratch, released on restart) —
        the kill-storm harness's bit-identical comparison against a
        never-crashed oracle vault."""
        import hashlib

        h = hashlib.sha256()
        with self._lock:
            for row in self._db.execute(
                "SELECT tx_id, output_index, contract, state_class,"
                " notary_name, state_blob, consumed, consumed_by,"
                " quantity, token FROM vault_states ORDER BY tx_id,"
                " output_index"
            ):
                h.update(repr(row).encode())
        return h.hexdigest()

    def snapshot_now(self) -> None:
        """Force a journal snapshot + WAL compaction (tests/operators)."""
        if self._journal is not None:
            # read the high-water mark BEFORE dumping: any record at or
            # below it was fully applied before its append, so the dump
            # taken after the read must include it
            lsn = self._journal_lsn
            self._journal.snapshot(self._dump_pages(), covered_lsn=lsn)

    # -- querying -------------------------------------------------------------

    def _build_query(self, criteria: QueryCriteria) -> tuple[str, list]:
        clauses, params = [], []
        if criteria.status is StateStatus.UNCONSUMED:
            clauses.append("consumed=0")
        elif criteria.status is StateStatus.CONSUMED:
            clauses.append("consumed=1")
        if criteria.contract_state_types:
            # accept classes or class-name strings — RPC clients send names
            names = [
                t if isinstance(t, str) else t.__name__
                for t in criteria.contract_state_types
            ]
            clauses.append(
                "state_class IN (%s)" % ",".join("?" * len(names))
            )
            params.extend(names)
        if criteria.state_refs:
            refs = criteria.state_refs
            ors = " OR ".join("(tx_id=? AND output_index=?)" for _ in refs)
            clauses.append(f"({ors})")
            for r in refs:
                params.extend((r.txhash.bytes, r.index))
        if criteria.notary_names:
            clauses.append(
                "notary_name IN (%s)" % ",".join("?" * len(criteria.notary_names))
            )
            params.extend(criteria.notary_names)
        if criteria.participant_keys:
            keys = criteria.participant_keys
            clauses.append(
                "EXISTS (SELECT 1 FROM vault_participants p WHERE"
                " p.tx_id=vault_states.tx_id AND p.output_index=vault_states.output_index"
                " AND p.participant_key IN (%s))" % ",".join("?" * len(keys))
            )
            params.extend(serialize(k) for k in keys)
        if not criteria.include_soft_locked:
            if criteria.soft_lock_id is not None:
                clauses.append("(lock_id IS NULL OR lock_id=?)")
                params.append(criteria.soft_lock_id)
            else:
                clauses.append("lock_id IS NULL")
        if criteria.token_repr is not None:
            clauses.append("token=?")
            params.append(criteria.token_repr)
        if criteria.quantity_geq is not None:
            clauses.append("quantity>=?")
            params.append(criteria.quantity_geq)
        where = " AND ".join(clauses) if clauses else "1=1"
        return where, params

    def query_by(
        self,
        criteria: QueryCriteria = QueryCriteria(),
        paging: PageSpecification | None = None,
        sort: Sort = Sort(),
    ) -> Page:
        where, params = self._build_query(criteria)
        col = Sort._COLUMNS[sort.by]
        order = f"{col} {'DESC' if sort.descending else 'ASC'}"
        limit = ""
        if paging is not None:
            limit = " LIMIT %d OFFSET %d" % (
                paging.page_size, (paging.page_number - 1) * paging.page_size,
            )
        with self._lock:
            total = self._db.execute(
                f"SELECT COUNT(*) FROM vault_states WHERE {where}", params
            ).fetchone()[0]
            rows = self._db.execute(
                f"SELECT tx_id, output_index, state_blob FROM vault_states"
                f" WHERE {where} ORDER BY {order}{limit}",
                params,
            ).fetchall()
        states = [
            StateAndRef(deserialize(blob), StateRef(SecureHash(tx_id), idx))
            for tx_id, idx, blob in rows
        ]
        return Page(states, total)

    def unconsumed_states(self, state_type: type | None = None) -> list[StateAndRef]:
        crit = QueryCriteria(
            contract_state_types=(state_type,) if state_type else None
        )
        return self.query_by(crit).states

    def untrack(self, callback) -> None:
        with self._lock:
            try:
                self._subscribers.remove(callback)
            except ValueError:
                pass

    def track(self, callback) -> Page:
        """Snapshot + subscription (reference: vaultTrackBy returning
        DataFeed<Vault.Page, Vault.Update>)."""
        with self._lock:
            snapshot = self.query_by()
            self._subscribers.append(callback)
        return snapshot

    # -- soft locking (reference: VaultSoftLockManager.kt) --------------------

    def soft_lock_reserve(self, lock_id: str, refs: list[StateRef]) -> None:
        """Atomically reserve unconsumed, unlocked states; raises and leaves
        nothing locked if any ref is unavailable."""
        with self._lock:
            for ref in refs:
                row = self._db.execute(
                    "SELECT consumed, lock_id FROM vault_states"
                    " WHERE tx_id=? AND output_index=?",
                    (ref.txhash.bytes, ref.index),
                ).fetchone()
                if (row is None or row[0] != 0
                        or (row[1] is not None and row[1] != lock_id)):
                    self._db.rollback()
                    raise SoftLockError(f"state {ref} unavailable for locking")
            for ref in refs:
                self._db.execute(
                    "UPDATE vault_states SET lock_id=? WHERE tx_id=? AND output_index=?",
                    (lock_id, ref.txhash.bytes, ref.index),
                )
            self._db.commit()

    def soft_lock_reacquire(self, lock_id: str, refs: list[StateRef]) -> int:
        """Best-effort re-reservation for flow REPLAY (crash restore or
        park/resume): re-lock every ref still unconsumed and free (or
        already ours), silently skipping the rest — a state the flow's own
        transaction has consumed since selection no longer needs the lock.
        Returns the number re-locked."""
        n = 0
        with self._lock:
            for ref in refs:
                cur = self._db.execute(
                    "UPDATE vault_states SET lock_id=?"
                    " WHERE tx_id=? AND output_index=? AND consumed=0"
                    " AND (lock_id IS NULL OR lock_id=?)",
                    (lock_id, ref.txhash.bytes, ref.index, lock_id),
                )
                n += cur.rowcount
            self._db.commit()
        return n

    def soft_lock_release(self, lock_id: str, refs: list[StateRef] | None = None) -> None:
        with self._lock:
            if refs is None:
                self._db.execute(
                    "UPDATE vault_states SET lock_id=NULL WHERE lock_id=?", (lock_id,)
                )
            else:
                for ref in refs:
                    self._db.execute(
                        "UPDATE vault_states SET lock_id=NULL"
                        " WHERE tx_id=? AND output_index=? AND lock_id=?",
                        (ref.txhash.bytes, ref.index, lock_id),
                    )
            self._db.commit()

    # -- coin selection (reference: CashSelectionH2Impl.kt shape) -------------

    def select_fungible(
        self, token, required_quantity: int, lock_id: str,
        state_type: type | None = None,
    ) -> list[StateAndRef]:
        """Greedy smallest-first selection of unconsumed fungible states
        totalling ≥ required_quantity; soft-locks the selection."""
        crit = QueryCriteria(
            contract_state_types=(state_type,) if state_type else None,
            include_soft_locked=False,
            soft_lock_id=lock_id,
            token_repr=_token_repr(token),
        )
        page = self.query_by(crit, sort=Sort(by="quantity"))
        picked, total = [], 0
        for sr in page.states:
            picked.append(sr)
            total += sr.state.data.amount.quantity
            if total >= required_quantity:
                break
        if total < required_quantity:
            raise SoftLockError(
                f"insufficient funds: have {total}, need {required_quantity}"
            )
        if self._state_index is not None:
            # device cross-check of the SQL selection: every picked ref
            # must be in the unconsumed index; a miss is counted, never
            # fatal (SQL is authoritative — see docs/STATE_STORE.md)
            bits = self._state_index.contains([sr.ref for sr in picked])
            if bits is not None and not all(bits):
                from corda_tpu.node.monitoring import node_metrics

                node_metrics().counter(
                    "statestore.vault.select_mismatch"
                ).inc(int(len(bits) - bits.sum()))
        self.soft_lock_reserve(lock_id, [sr.ref for sr in picked])
        return picked

    def unconsumed_ref_exists(self, ref: StateRef) -> bool:
        """Membership of one ref in the UNCONSUMED page — answered by
        the device index when one is attached (falling back to SQL on a
        probe failure), by SQL otherwise."""
        if self._state_index is not None:
            bits = self._state_index.contains([ref])
            if bits is not None:
                return bool(bits[0])
        with self._lock:
            row = self._db.execute(
                "SELECT 1 FROM vault_states"
                " WHERE tx_id=? AND output_index=? AND consumed=0",
                (ref.txhash.bytes, ref.index),
            ).fetchone()
        return row is not None

    def close(self) -> None:
        if self._journal is not None:
            self._journal.flush()
            self._journal.close()
        with self._lock:
            self._db.close()


# -------------------------------------------------- wire registrations
# Query/page types travel over RPC (vault_query_by args and results);
# state types inside criteria are encoded by class NAME (the column the
# vault filters on), so clients need not hold the classes.

register_custom(
    QueryCriteria, "vault.QueryCriteria",
    to_fields=lambda c: {
        "status": c.status.value,
        "types": [
            t if isinstance(t, str) else t.__name__
            for t in (c.contract_state_types or [])
        ] or 0,
        "state_refs": list(c.state_refs) if c.state_refs else 0,
        "notary_names": list(c.notary_names) if c.notary_names else 0,
        "participant_keys": (
            list(c.participant_keys) if c.participant_keys else 0
        ),
        "include_soft_locked": 1 if c.include_soft_locked else 0,
        "soft_lock_id": c.soft_lock_id or "",
        "quantity_geq": -1 if c.quantity_geq is None else c.quantity_geq,
        "token_repr": c.token_repr or "",
    },
    from_fields=lambda d: QueryCriteria(
        status=StateStatus(d["status"]),
        contract_state_types=tuple(d["types"]) if d["types"] != 0 else None,
        state_refs=tuple(d["state_refs"]) if d["state_refs"] != 0 else None,
        notary_names=(
            tuple(d["notary_names"]) if d["notary_names"] != 0 else None
        ),
        participant_keys=(
            tuple(d["participant_keys"])
            if d["participant_keys"] != 0 else None
        ),
        include_soft_locked=bool(d["include_soft_locked"]),
        soft_lock_id=d["soft_lock_id"] or None,
        quantity_geq=None if d["quantity_geq"] == -1 else d["quantity_geq"],
        token_repr=d["token_repr"] or None,
    ),
)
register_custom(
    PageSpecification, "vault.PageSpecification",
    to_fields=lambda p: {"page_number": p.page_number, "page_size": p.page_size},
    from_fields=lambda d: PageSpecification(d["page_number"], d["page_size"]),
)
register_custom(
    Sort, "vault.Sort",
    to_fields=lambda s: {"by": s.by, "descending": 1 if s.descending else 0},
    from_fields=lambda d: Sort(d["by"], bool(d["descending"])),
)
register_custom(
    Page, "vault.Page",
    to_fields=lambda p: {
        "states": list(p.states),
        "total": p.total_states_available,
    },
    from_fields=lambda d: Page(list(d["states"]), d["total"]),
)
register_custom(
    VaultUpdate, "vault.Update",
    to_fields=lambda u: {
        "consumed": list(u.consumed), "produced": list(u.produced),
    },
    from_fields=lambda d: VaultUpdate(
        tuple(d["consumed"]), tuple(d["produced"])
    ),
)
