"""Node services layer (L5/L6/L9 of SURVEY.md §1).

The capability surface of the reference node runtime
(node/src/main/kotlin/net/corda/node/services/): a ``ServiceHub`` service
locator composing vault, identity, key-management, attachment, network-map,
transaction-storage, scheduler and verifier services, plus typed
configuration and a metrics registry — re-designed host-side (SQLite-backed
persistence, callback feeds instead of Rx Observables) around the TPU
verification tier.
"""

from .config import (
    NodeConfiguration,
    NotaryConfig,
    RaftConfig,
    BFTConfig,
    VerifierType,
    load_config,
)
from .identity import IdentityService, KeyManagementService
from .monitoring import Counter, Gauge, Meter, MetricRegistry, Timer
from .network_map import (
    NetworkMapCache,
    NetworkMapClient,
    NetworkMapServer,
    NodeInfo,
)
from .scheduler import NodeSchedulerService, ScheduledActivity, SchedulableState
from .node import Node
from .services import ServiceHub, TransactionResolutionError
from .storage import Attachment, AttachmentStorage, DBTransactionStorage
from .vault import (
    NodeVaultService,
    PageSpecification,
    QueryCriteria,
    Sort,
    SoftLockError,
    StateStatus,
    Vault,
    VaultUpdate,
)

__all__ = [
    "NodeConfiguration", "NotaryConfig", "RaftConfig", "BFTConfig",
    "VerifierType", "load_config",
    "IdentityService", "KeyManagementService",
    "Counter", "Gauge", "Meter", "MetricRegistry", "Timer",
    "NetworkMapCache", "NetworkMapClient", "NetworkMapServer", "NodeInfo",
    "NodeSchedulerService", "ScheduledActivity", "SchedulableState",
    "Node",
    "ServiceHub", "TransactionResolutionError",
    "Attachment", "AttachmentStorage", "DBTransactionStorage",
    "NodeVaultService", "PageSpecification", "QueryCriteria", "Sort",
    "SoftLockError", "StateStatus", "Vault", "VaultUpdate",
]
