"""The node container: configuration → a running node.

Capability parity with the reference's boot path (node/.../Corda.kt:7 →
NodeStartup.kt:30 → AbstractNode.start(), AbstractNode.kt:202-255): from a
``NodeConfiguration``, assemble persistence, services (vault, identity,
keys, attachments, network map, scheduler), the verifier service selected
by ``verifierType``, the notary service selected by the notary config
(simple / validating / batched / Raft / BFT —
AbstractNode.makeCoreNotaryService :615-632), the flow state machine, and
the RPC server; register with the network map; start the scheduler;
restore checkpointed flows.

Transport is injected (an ``InMemoryMessagingNetwork`` for in-process
ensembles — the driver/demo mode — or a ``DurableQueueBroker`` client for
crash-durable messaging; a gRPC transport slots in the same interface for
multi-host DCN deployment).
"""

from __future__ import annotations

import logging
from pathlib import Path

from corda_tpu.crypto import generate_keypair
from corda_tpu.flows import CheckpointStorage, StateMachineManager
from corda_tpu.ledger import CordaX500Name, Party
from corda_tpu.verifier import BatchedVerifierService, InMemoryVerifierService

from .config import NodeConfiguration, VerifierType
from .identity import IdentityService, KeyManagementService
from .network_map import NetworkMapCache, NodeInfo
from .scheduler import NodeSchedulerService
from .services import ServiceHub
from .storage import AttachmentStorage, DBTransactionStorage
from .vault import NodeVaultService

logger = logging.getLogger(__name__)


class Node:
    """A fully-assembled node (reference: AbstractNode + Node)."""

    def __init__(
        self,
        config: NodeConfiguration,
        messaging,
        network_map: NetworkMapCache | None = None,
        party_resolver=None,
        keypair=None,
        persistent: bool = False,
    ):
        self.config = config
        self.messaging = messaging
        # CorDapp loading (reference: CordappLoader.kt:41-63) — named
        # packages plus the plugins-directory scan; the loader records a
        # manifest of what each app registered (contracts, responders,
        # wire types) for the provider queries
        from corda_tpu.node.cordapp import CordappLoader

        self.cordapp_loader = CordappLoader()
        for pkg in config.cordapp_packages:
            self.cordapp_loader.load_package(pkg)
        if config.cordapp_directory:
            self.cordapp_loader.load_directory(config.cordapp_directory)
        if config.mesh_fan_out is not None:
            # force the device-mesh fan-out policy (default: auto when
            # multiple accelerator devices are visible)
            from corda_tpu.parallel import enable_service_mesh

            enable_service_mesh(config.mesh_fan_out)
        name = CordaX500Name.parse(config.my_legal_name) if isinstance(
            config.my_legal_name, str
        ) else config.my_legal_name
        self.keypair = keypair or generate_keypair()
        self.party = Party(name, self.keypair.public)
        notary_mode = ""
        if config.notary is not None:
            notary_mode = "validating" if config.notary.validating else "simple"
        self.info = NodeInfo(
            (config.p2p_address,), (self.party,), notary_mode=notary_mode
        )
        # peers address us by the canonical X.500 string — a transport
        # endpoint registered under anything else silently receives nothing
        expected = str(self.party.name)
        if messaging.me.name != expected:
            raise ValueError(
                f"messaging endpoint is {messaging.me.name!r} but peers "
                f"will address {expected!r} — create the transport node "
                "with str(CordaX500Name.parse(config.my_legal_name))"
            )

        base = Path(config.base_directory)
        if persistent:
            base.mkdir(parents=True, exist_ok=True)
        db = (lambda f: str(base / f)) if persistent else (lambda f: ":memory:")
        self._durable_store_for = self._make_durability_factory(base)

        network_map = network_map or NetworkMapCache()
        identity_service = IdentityService()
        kms = KeyManagementService([self.keypair], identity_service)
        self._notary_uniqueness = None
        notary_service = self._make_notary_service(db)
        self.services = ServiceHub(
            my_info=self.info,
            key_management_service=kms,
            identity_service=identity_service,
            vault_service=NodeVaultService(
                db("vault.db"), my_keys=kms.keys,
                journal=self._durable_store_for("vault"),
            ),
            validated_transactions=DBTransactionStorage(db("transactions.db")),
            attachments=AttachmentStorage(db("attachments.db")),
            network_map_cache=network_map,
            verifier_service=self._make_verifier_service(),
            notary_service=notary_service,
        )
        # attachment-carried contract code: the verify path resolves
        # unknown contract names from transaction attachments through this
        # store (ledger/attachment_code.py; reference:
        # AttachmentsClassLoader.kt:24)
        from corda_tpu.ledger.attachment_code import set_attachment_fetcher

        attachments_store = self.services.attachments

        def _fetch(att_id):
            att = attachments_store.open_attachment(att_id)
            return att.data if att is not None else None

        set_attachment_fetcher(_fetch)
        if party_resolver is None:
            def party_resolver(sender_name: str):
                info = network_map.get_node_by_legal_name(
                    CordaX500Name.parse(sender_name)
                )
                return info.legal_identity if info else None
        flow_store = self._durable_store_for("flows")
        if flow_store is not None:
            from corda_tpu.flows import WalCheckpointStorage

            checkpoints = WalCheckpointStorage(flow_store)
        else:
            checkpoints = CheckpointStorage(db("checkpoints.db"))
        self.smm = StateMachineManager(
            messaging,
            checkpoints,
            self.party,
            party_resolver,
            services=self.services,
        )
        # imported here, not at module level: rpc depends on node.vault,
        # so a module-level import would make corda_tpu.rpc unimportable
        # on its own (circular) — deferred, both import orders work
        from corda_tpu.rpc import CordaRPCOps, RPCServer

        self.rpc_ops = CordaRPCOps(self.services, self.smm)
        self.rpc_server = RPCServer(
            self.rpc_ops, messaging, rpc_users=config.rpc_users
        )
        from .scheduler import make_scheduled_flow_starter

        self._start_scheduled_flow = make_scheduled_flow_starter(
            self.smm, self.party.name
        )
        self.scheduler = NodeSchedulerService(self._start_scheduled_flow)
        self.services.scheduler_service = self.scheduler
        # SchedulableState outputs recorded to the vault drive time-based
        # flow starts (reference: ScheduledActivityObserver wired in
        # AbstractNode); the track snapshot re-derives schedules on restart
        self.scheduler.observe_vault(self.services.vault_service)
        # app-provided node services (reference: @CordaService classes
        # instantiated in AbstractNode.installCordaServices) — only those
        # defined by cordapps THIS node's config loaded
        from corda_tpu.node.cordapp import install_corda_services

        install_corda_services(
            self.services, self.party, self.keypair,
            loaded_modules={
                app.module for app in self.cordapp_loader.cordapps
            },
        )
        self._started = False

    # ------------------------------------------------------------ assembly
    def _make_durability_factory(self, base: Path):
        """Owner-name → DurableStore factory, or a None-returning stub
        when durability is off (the default: nothing imported beyond the
        cheap enabled() probe, no files opened, no metrics created —
        docs/DURABILITY.md). Enabled with ``CORDA_TPU_DURABILITY=1``; the
        base directory is ``CORDA_TPU_WAL_DIR`` (one subdirectory per
        node name, so in-process ensembles sharing the env don't collide)
        or the node's own base directory."""
        from corda_tpu.durability import durability_enabled, store_for

        if not durability_enabled():
            return lambda owner: None
        import os as _os
        import re as _re

        env_base = _os.environ.get("CORDA_TPU_WAL_DIR", "")
        # per-node-name slug in BOTH branches: in-process ensembles whose
        # configs share a base_directory (the default ".") must not share
        # one WAL directory — two WriteAheadLogs on the same files would
        # truncate each other's live tail segments
        slug = _re.sub(r"[^A-Za-z0-9_.=,-]", "_", str(self.party.name))
        root = _os.path.join(env_base or str(base / "durability"), slug)
        return lambda owner: store_for(owner, base_dir=root)

    def _make_verifier_service(self):
        vt = self.config.verifier_type
        if vt is VerifierType.DeviceBatched:
            return BatchedVerifierService(
                max_batch=self.config.verification_batch_max,
                window_s=self.config.verification_window_ms / 1000.0,
            )
        if vt is VerifierType.OutOfProcess:
            # external workers compete on the broker's verifier.requests
            # queue (reference: Node.makeTransactionVerifierService →
            # NodeMessagingClient.verifierService, Node.kt:103)
            broker = getattr(self.messaging, "_broker", None)
            if broker is not None:
                from corda_tpu.verifier.worker import (
                    OutOfProcessVerifierService,
                )

                return OutOfProcessVerifierService(
                    broker, str(self.party.name)
                )
            logger.warning(
                "verifierType=OutOfProcess needs a broker transport; "
                "falling back to the in-process pool"
            )
        return InMemoryVerifierService()

    def _make_notary_service(self, db):
        """reference: AbstractNode.makeCoreNotaryService
        (AbstractNode.kt:615-632) — notary flavor from config."""
        cfg = self.config.notary
        if cfg is None:
            return None
        from corda_tpu.notary import PersistentUniquenessProvider
        from corda_tpu.notary.service import (
            SimpleNotaryService,
            ValidatingNotaryService,
        )

        if cfg.raft is not None:
            # multi-process CFT cluster: this node is one Raft replica,
            # speaking raft.* topics over its own fabric endpoint to the
            # peers named in clusterAddresses (reference: the out-of-
            # process Copycat cluster, NodeConfiguration.kt:45). Started
            # with the node (start()/stop()).
            from corda_tpu.notary import RaftUniquenessProvider

            me = str(self.party.name)
            # replica names ARE fabric endpoint names (canonical X.500
            # node names — the shape the process driver generates); a
            # nodeAddress differing from this node's name, or a peer
            # entry that isn't an X.500 name (e.g. a reference-style
            # host:port), would yield divergent/unresolvable membership
            # and the cluster would hang without quorum. Fail fast.
            if cfg.raft.node_address and cfg.raft.node_address != me:
                raise ValueError(
                    f"raft nodeAddress {cfg.raft.node_address!r} must equal "
                    f"this node's name {me!r} (replicas are addressed by "
                    "node name on the messaging fabric)"
                )
            from corda_tpu.ledger import CordaX500Name

            peers = set()
            for peer in cfg.raft.cluster_addresses:
                try:
                    # accept any valid X.500 spelling; members resolve by
                    # the CANONICAL form (which is what node endpoints
                    # register as)
                    peers.add(str(CordaX500Name.parse(peer)))
                except Exception:
                    raise ValueError(
                        f"raft clusterAddresses entry {peer!r} is not an "
                        "X.500 node name — replicas are addressed by node "
                        "name on the messaging fabric, not host:port"
                    ) from None
            names = sorted({me, *peers})
            storage_path = db("raft.db")
            uniqueness = RaftUniquenessProvider.make_node_on_endpoint(
                me, names, self.messaging,
                storage_path=(
                    storage_path if storage_path != ":memory:" else None
                ),
            )
        else:
            # BFT clusters remain externally wired (they need the whole
            # replica set's keys up front); the container builds the
            # single-replica and Raft tiers
            notary_store = self._durable_store_for("notary")
            from corda_tpu.statestore import statestore_enabled

            if statestore_enabled():
                # device-resident consumed set (docs/STATE_STORE.md);
                # the durable store, when configured, becomes its
                # recovery/spill journal
                from corda_tpu.statestore import (
                    DeviceShardedUniquenessProvider,
                )

                uniqueness = DeviceShardedUniquenessProvider(notary_store)
            elif notary_store is not None:
                from corda_tpu.notary import DurableUniquenessProvider

                uniqueness = DurableUniquenessProvider(notary_store)
            else:
                uniqueness = PersistentUniquenessProvider(db("notary.db"))
        self._notary_uniqueness = uniqueness
        cls = ValidatingNotaryService if cfg.validating else SimpleNotaryService
        return cls(self.party, self.keypair, uniqueness)

    def set_notary_uniqueness_provider(self, provider) -> None:
        """Swap in a replicated (Raft/BFT) uniqueness provider built by the
        cluster driver before ``start()``. The container-built local
        provider is closed and fully replaced."""
        if self.services.notary_service is None:
            raise ValueError("node has no notary service")
        old = self._notary_uniqueness
        if old is not None and hasattr(old, "close"):
            old.close()
        self._notary_uniqueness = provider
        self.services.notary_service.uniqueness = provider

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "Node":
        # add_node also registers us as a notary when info.notary_mode is
        # set — single source of truth for the mode
        self.services.network_map_cache.add_node(self.info)
        raft_node = getattr(self._notary_uniqueness, "node", None)
        if raft_node is not None:
            raft_node.start()
        self.scheduler.start()
        restored = self.smm.restore()
        if restored:
            logger.info(
                "%s: restored %d checkpointed flow(s)",
                self.party.name, len(restored),
            )
        self._started = True
        return self

    def run_flow(self, flow, timeout: float = 60):
        return self.smm.start_flow(flow).result.result(timeout=timeout)

    def stop(self) -> None:
        self.scheduler.stop()
        self.rpc_server.stop()
        self.smm.stop()
        # the durable checkpoint tier owns an open WAL tail: release it
        # on stop so an in-process restart (the chaos orchestrator's
        # restart_fn shape) never has two handles appending to one
        # segment. The legacy sqlite storage keeps its historical
        # never-closed semantics.
        from corda_tpu.flows import WalCheckpointStorage

        if isinstance(self.smm.checkpoints, WalCheckpointStorage):
            self.smm.checkpoints.close()
        self.services.shutdown()
        fabric_server = getattr(self, "fabric_server", None)
        if fabric_server is not None:
            fabric_server.close()
        fabric_client = getattr(self, "fabric_client", None)
        if fabric_client is not None:
            fabric_client.close()
        if self._notary_uniqueness is not None and hasattr(
            self._notary_uniqueness, "close"
        ):
            self._notary_uniqueness.close()
        self._started = False

    def __repr__(self):
        return f"Node({self.party.name}, started={self._started})"
