from .cbe import (
    GenericRecord,
    SerializationError,
    cbe_serializable,
    decode,
    deserialize,
    encode,
    register_custom,
    register_rename,
    serialize,
)
from .carpenter import CarpenterError, ClassCarpenter, carpent

__all__ = [
    "GenericRecord",
    "SerializationError",
    "cbe_serializable",
    "decode",
    "deserialize",
    "encode",
    "register_custom",
    "register_rename",
    "serialize",
    "CarpenterError",
    "ClassCarpenter",
    "carpent",
]
