from .cbe import (
    GenericRecord,
    SerializationError,
    cbe_serializable,
    decode,
    deserialize,
    encode,
    register_custom,
    serialize,
)

__all__ = [
    "GenericRecord",
    "SerializationError",
    "cbe_serializable",
    "decode",
    "deserialize",
    "encode",
    "register_custom",
    "serialize",
]
