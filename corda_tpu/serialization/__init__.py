from .cbe import (
    GenericRecord,
    SerializationError,
    cbe_serializable,
    decode,
    deserialize,
    encode,
    register_custom,
    serialize,
)
from .carpenter import CarpenterError, ClassCarpenter, carpent

__all__ = [
    "GenericRecord",
    "SerializationError",
    "cbe_serializable",
    "decode",
    "deserialize",
    "encode",
    "register_custom",
    "serialize",
    "CarpenterError",
    "ClassCarpenter",
    "carpent",
]
