"""CBE — Canonical Binary Encoding.

The single deterministic wire/storage format of the framework, replacing the
reference's dual Kryo/AMQP stack (reference: node-api/.../internal/serialization,
core/.../serialization/SerializationAPI.kt). Design goals, in order:

1. **Determinism** — byte-identical encoding for equal values (map keys are
   sorted by their encoded bytes; no timestamps, no identity hashes). Transaction
   ids are Merkle roots over CBE bytes, so this is a consensus-critical property.
2. **Self-description + evolution** — objects carry their type name and field
   names; unknown types decode into :class:`GenericRecord` (the equivalent of the
   reference's class "carpenter", node-api/.../serialization/carpenter/), and
   registered types tolerate added/removed fields with defaults (the equivalent
   of the AMQP ``EvolutionSerializer``).
3. **Zero dependencies and a tiny grammar** — so a C++/device-side decoder stays
   trivial.

Grammar (one tag byte, then payload):
    0x00 None            0x01 False            0x02 True
    0x03 int             zigzag varint
    0x04 bytes           varint len + raw
    0x05 str             varint len + utf8
    0x06 list/tuple      varint count + items
    0x07 map             varint count + (key, value)*, sorted by encoded key
    0x08 object          str type-name + map of fields
    0x09 float64         8 bytes big-endian IEEE754
    0x0A set             varint count + items sorted by encoded bytes

Top-level envelope: magic ``CT`` + version byte 0x01 + value (the versioned
header mirrors the reference's ``KryoHeaderV0_1`` scheme-negotiation byte
prefix, SerializationScheme.kt).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Callable

MAGIC = b"CT\x01"

_T_NONE = 0x00
_T_FALSE = 0x01
_T_TRUE = 0x02
_T_INT = 0x03
_T_BYTES = 0x04
_T_STR = 0x05
_T_LIST = 0x06
_T_MAP = 0x07
_T_OBJ = 0x08
_T_FLOAT = 0x09
_T_SET = 0x0A

# type-name -> (class, from_fields) registry for registered serializable types
_REGISTRY: dict[str, tuple[type, Callable[[dict], Any]]] = {}
# class -> (type-name, to_fields)
_ENCODERS: dict[type, tuple[str, Callable[[Any], dict]]] = {}


class SerializationError(Exception):
    pass


@dataclasses.dataclass(frozen=True)
class GenericRecord:
    """Decoded stand-in for a type not registered locally.

    Parity with the reference's class carpenter: a peer can send us an object
    of a type we don't have; we still get a structured, re-encodable value.
    """

    type_name: str
    fields: tuple  # tuple of (name, value) pairs, in encoded order

    def __getattr__(self, name):
        for k, v in object.__getattribute__(self, "fields"):
            if k == name:
                return v
        raise AttributeError(name)

    def as_dict(self) -> dict:
        return dict(self.fields)


def cbe_serializable(cls=None, *, name: str | None = None,
                     renamed_from: tuple = (),
                     field_aliases: dict | None = None):
    """Class decorator registering a dataclass for CBE object encoding.

    The equivalent of the reference's ``@CordaSerializable`` marker
    (core/.../serialization/SerializationAPI.kt) — but opt-in registration
    doubles as the serialization *whitelist* (CordaClassResolver parity):
    only registered types round-trip to their Python class; everything else
    surfaces as :class:`GenericRecord`.

    Evolution (the role of the reference's ``EvolutionSerializer``,
    node-api/.../serialization/amqp/EvolutionSerializer.kt — a rolling
    upgrade must let old and new versions of a type cross the wire in both
    directions without wedging either side):

    - **added field** (old writer → new reader): absent fields take the
      dataclass default; a field added *without* a default raises a clean
      ``SerializationError`` naming the type, never a bare TypeError.
    - **removed field** (old writer → new reader): unknown keys in the
      payload are dropped (the new class no longer carries them).
    - **renamed field**: ``field_aliases={"new_name": "old_name"}`` maps an
      old writer's key onto the renamed field (the
      CordaSerializationTransformRenames role).
    - **renamed type**: ``renamed_from=("old.wire.Name", ...)`` registers
      decode aliases so payloads tagged with a retired type name decode
    through the current class; encoding always uses the current name.
    """
    aliases = dict(field_aliases or {})

    def wrap(c):
        type_name = name or f"{c.__module__.split('.')[-1]}.{c.__qualname__}"
        if not dataclasses.is_dataclass(c):
            raise SerializationError(f"@cbe_serializable requires a dataclass: {c}")
        field_names = [f.name for f in dataclasses.fields(c)]

        def to_fields(obj) -> dict:
            return {fn: getattr(obj, fn) for fn in field_names}

        def from_fields(d: dict):
            known = {f.name for f in dataclasses.fields(c)}
            kwargs = {k: v for k, v in d.items() if k in known}
            for new, old in aliases.items():
                if new not in kwargs and old in d:
                    kwargs[new] = d[old]
            try:
                return c(**kwargs)
            except TypeError as e:
                raise SerializationError(
                    f"evolution mismatch decoding {type_name!r}: {e} — a "
                    "field added after a writer's version must carry a "
                    "default"
                ) from None

        _REGISTRY[type_name] = (c, from_fields)
        _ENCODERS[c] = (type_name, to_fields)
        c.__cbe_name__ = type_name
        for old_name in renamed_from:
            register_rename(old_name, c)
        return c

    return wrap(cls) if cls is not None else wrap


def register_rename(old_name: str, cls: type) -> None:
    """Alias a retired wire name onto ``cls``'s current registration, so
    payloads written by peers still running the old type name decode into
    the current class (renamed-type evolution). The current name stays the
    only one encoded."""
    current = _ENCODERS.get(cls)
    if current is None:
        raise SerializationError(
            f"{cls.__qualname__} must be registered before aliasing "
            f"{old_name!r} to it"
        )
    existing = _REGISTRY.get(old_name)
    if existing is not None and existing[0] is not cls:
        raise SerializationError(
            f"serialization name {old_name!r} already registered for "
            f"{existing[0].__qualname__}; refusing to alias to "
            f"{cls.__qualname__}"
        )
    _REGISTRY[old_name] = (cls, _REGISTRY[current[0]][1])


def register_custom(cls: type, name: str, to_fields, from_fields) -> None:
    """Register a non-dataclass type with explicit field mappers.

    Re-registering a name with a *different* class is rejected: the registry
    is the wire-format whitelist (the reference's CordaClassResolver refuses
    unregistered/ambiguous classes for the same reason), and a silent
    overwrite would let one component's encoder feed another's decoder.
    """
    existing = _REGISTRY.get(name)
    if existing is not None and existing[0] is not cls:
        raise SerializationError(
            f"serialization name {name!r} already registered for "
            f"{existing[0].__qualname__}; refusing to rebind to "
            f"{cls.__qualname__}"
        )
    _REGISTRY[name] = (cls, from_fields)
    _ENCODERS[cls] = (name, to_fields)
    cls.__cbe_name__ = name


# ---------------------------------------------------------------- varints

def _write_uvarint(buf: bytearray, n: int) -> None:
    if n < 0:
        raise SerializationError("uvarint must be non-negative")
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        if pos >= len(data):
            raise SerializationError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            # Canonical-form enforcement: the encoding of a value must be
            # unique, so a non-minimal final byte (0x00 continuation) is
            # rejected. Consensus-critical: tx ids hash CBE bytes.
            if b == 0 and shift > 0:
                raise SerializationError("non-minimal varint")
            return result, pos
        shift += 7
        if shift > 640:
            raise SerializationError("varint too long")


def _zigzag(n: int) -> int:
    return (n << 1) if n >= 0 else ((-n << 1) - 1)


def _unzigzag(n: int) -> int:
    return (n >> 1) if not (n & 1) else -((n + 1) >> 1)


# ---------------------------------------------------------------- encode

def _encode(buf: bytearray, obj: Any) -> None:
    if obj is None:
        buf.append(_T_NONE)
    elif obj is True:
        buf.append(_T_TRUE)
    elif obj is False:
        buf.append(_T_FALSE)
    elif isinstance(obj, int):
        buf.append(_T_INT)
        _write_uvarint(buf, _zigzag(obj))
    elif isinstance(obj, float):
        buf.append(_T_FLOAT)
        buf += struct.pack(">d", obj)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        buf.append(_T_BYTES)
        b = bytes(obj)
        _write_uvarint(buf, len(b))
        buf += b
    elif isinstance(obj, str):
        buf.append(_T_STR)
        b = obj.encode("utf-8")
        _write_uvarint(buf, len(b))
        buf += b
    elif type(obj) in _ENCODERS:
        type_name, to_fields = _ENCODERS[type(obj)]
        buf.append(_T_OBJ)
        nb = type_name.encode("utf-8")
        _write_uvarint(buf, len(nb))
        buf += nb
        _encode_map(buf, to_fields(obj))
    elif isinstance(obj, GenericRecord):
        buf.append(_T_OBJ)
        nb = obj.type_name.encode("utf-8")
        _write_uvarint(buf, len(nb))
        buf += nb
        _encode_map(buf, dict(obj.fields))
    elif isinstance(obj, (list, tuple)):
        buf.append(_T_LIST)
        _write_uvarint(buf, len(obj))
        for item in obj:
            _encode(buf, item)
    elif isinstance(obj, dict):
        _encode_map(buf, obj)
    elif isinstance(obj, (set, frozenset)):
        buf.append(_T_SET)
        _write_uvarint(buf, len(obj))
        encoded = sorted(encode(item) for item in obj)
        for e in encoded:
            buf += e
    else:
        raise SerializationError(
            f"type {type(obj).__name__} is not CBE-serializable (register it "
            f"with @cbe_serializable)"
        )


def _encode_map(buf: bytearray, d: dict) -> None:
    buf.append(_T_MAP)
    _write_uvarint(buf, len(d))
    entries = sorted((encode(k), encode(v)) for k, v in d.items())
    for ek, ev in entries:
        buf += ek
        buf += ev


def encode(obj: Any) -> bytes:
    """Encode a single value, without the envelope."""
    buf = bytearray()
    _encode(buf, obj)
    return bytes(buf)


def serialize(obj: Any) -> bytes:
    """Encode with the versioned envelope — the public entry point."""
    return MAGIC + encode(obj)


# ---------------------------------------------------------------- decode

def _decode(data: bytes, pos: int) -> tuple[Any, int]:
    if pos >= len(data):
        raise SerializationError("truncated value")
    tag = data[pos]
    pos += 1
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_INT:
        n, pos = _read_uvarint(data, pos)
        return _unzigzag(n), pos
    if tag == _T_FLOAT:
        if pos + 8 > len(data):
            raise SerializationError("truncated float")
        return struct.unpack(">d", data[pos:pos + 8])[0], pos + 8
    if tag == _T_BYTES:
        n, pos = _read_uvarint(data, pos)
        if pos + n > len(data):
            raise SerializationError("truncated bytes")
        return data[pos:pos + n], pos + n
    if tag == _T_STR:
        n, pos = _read_uvarint(data, pos)
        if pos + n > len(data):
            raise SerializationError("truncated str")
        return data[pos:pos + n].decode("utf-8"), pos + n
    if tag == _T_LIST:
        n, pos = _read_uvarint(data, pos)
        items = []
        for _ in range(n):
            item, pos = _decode(data, pos)
            items.append(item)
        return items, pos
    if tag == _T_SET:
        n, pos = _read_uvarint(data, pos)
        items = []
        prev_enc = None
        for _ in range(n):
            start = pos
            item, pos = _decode(data, pos)
            enc = data[start:pos]
            if prev_enc is not None and enc <= prev_enc:
                raise SerializationError("non-canonical set: items not strictly sorted")
            prev_enc = enc
            items.append(item)
        return frozenset(items), pos
    if tag == _T_MAP:
        n, pos = _read_uvarint(data, pos)
        d = {}
        prev_enc = None
        for _ in range(n):
            start = pos
            k, pos = _decode(data, pos)
            enc = data[start:pos]
            if prev_enc is not None and enc <= prev_enc:
                raise SerializationError("non-canonical map: keys not strictly sorted")
            prev_enc = enc
            v, pos = _decode(data, pos)
            d[k] = v
        return d, pos
    if tag == _T_OBJ:
        n, pos = _read_uvarint(data, pos)
        type_name = data[pos:pos + n].decode("utf-8")
        pos += n
        fields, pos = _decode(data, pos)
        if not isinstance(fields, dict):
            raise SerializationError("object fields must be a map")
        if type_name in _REGISTRY:
            _, from_fields = _REGISTRY[type_name]
            return from_fields(fields), pos
        # Map decode already enforced canonical key order, so insertion order
        # IS the encoded order (and mixed-type keys must not crash here).
        return GenericRecord(type_name, tuple(fields.items())), pos
    raise SerializationError(f"unknown CBE tag 0x{tag:02x}")


def decode(data: bytes) -> Any:
    obj, pos = _decode(data, 0)
    if pos != len(data):
        raise SerializationError(f"{len(data) - pos} trailing bytes")
    return obj


def deserialize(data: bytes) -> Any:
    if data[:3] != MAGIC:
        raise SerializationError("bad CBE envelope magic")
    return decode(data[3:])
