"""The class carpenter: synthesize USABLE classes for unknown wire types.

Capability parity with the reference's ClassCarpenter
(node-api/.../serialization/carpenter/ClassCarpenter.kt — when a peer
sends an object of a class we don't have, synthesize a JVM class from the
AMQP schema at runtime so the object is a first-class value, not an
opaque blob; MetaCarpenter handles nested schemas). Here the wire format
is CBE and unknown types decode to :class:`GenericRecord` (read-only);
the carpenter turns those into real frozen dataclasses — constructible,
attribute-complete, re-encodable under the original type name — and
REGISTERS them so subsequent decodes of the same type produce instances
directly.

Evolution: a later record carrying additional fields WIDENS the
synthesized class (re-synthesized with the union of fields, new ones
defaulting to None) — the carpenter analogue of the AMQP
EvolutionSerializer's default-filling.

Safety: the carpenter never shadows a genuinely registered class — if the
type name is already bound to a real implementation, that wins.
"""

from __future__ import annotations

import dataclasses
import keyword
import re
import threading

from .cbe import _ENCODERS, _REGISTRY, GenericRecord, SerializationError

_NAME_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*$")


class CarpenterError(SerializationError):
    pass


class ClassCarpenter:
    """Synthesizes and registers dataclasses from wire schemas."""

    def __init__(self):
        self._lock = threading.Lock()
        self._built: dict[str, type] = {}

    # ------------------------------------------------------------ schema

    @staticmethod
    def _check_fields(type_name: str, field_names) -> list[str]:
        out = []
        for f in field_names:
            if (not isinstance(f, str) or not _NAME_RE.match(f)
                    or keyword.iskeyword(f) or f.startswith("__")):
                # dunder names would override object protocol methods on
                # the synthesized class — a hostile peer must not get that
                raise CarpenterError(
                    f"cannot carpent {type_name!r}: invalid field {f!r}"
                )
            out.append(f)
        return out

    def build(self, type_name: str, field_names) -> type:
        """Get-or-synthesize the class for ``type_name`` with (at least)
        ``field_names``. Registered real classes always win."""
        existing = _REGISTRY.get(type_name)
        if existing is not None and existing[0] not in self._built.values():
            return existing[0]
        fields = self._check_fields(type_name, field_names)
        with self._lock:
            cls = self._built.get(type_name)
            if cls is not None:
                have = [f.name for f in dataclasses.fields(cls)]
                missing = [f for f in fields if f not in have]
                if not missing:
                    return cls
                fields = have + missing  # widen (schema evolution)
            cls = dataclasses.make_dataclass(
                type_name.rpartition(".")[2] or "Carpented",
                [(f, object, dataclasses.field(default=None)) for f in fields],
                frozen=True,
                namespace={
                    "__cbe_name__": type_name,
                    "__carpented__": True,
                    "__module__": __name__,
                },
            )
            self._register(type_name, cls)
            self._built[type_name] = cls
            return cls

    def _register(self, type_name: str, cls: type) -> None:
        field_names = [f.name for f in dataclasses.fields(cls)]
        known = set(field_names)

        def to_fields(obj) -> dict:
            return {fn: getattr(obj, fn) for fn in field_names}

        def from_fields(d: dict):
            extra = set(d) - known
            if extra:
                # decode-time schema widening: the peer evolved the type —
                # re-synthesize with the union and decode through that
                wider = self.build(type_name, list(d))
                if wider is not cls:
                    _, wider_from = _REGISTRY[type_name]
                    return wider_from(d)
            return cls(**{k: v for k, v in d.items() if k in known})

        _REGISTRY[type_name] = (cls, from_fields)
        _ENCODERS[cls] = (type_name, to_fields)

    # ------------------------------------------------------------ values

    def carpent(self, value):
        """Recursively convert GenericRecords inside ``value`` into
        synthesized-class instances (MetaCarpenter's nested-schema role)."""
        if isinstance(value, GenericRecord):
            cls = self.build(value.type_name, [k for k, _ in value.fields])
            if not getattr(cls, "__carpented__", False):
                # a real class got registered meanwhile: decode through it
                _, from_fields = _REGISTRY[value.type_name]
                return from_fields({
                    k: self.carpent(v) for k, v in value.fields
                })
            return cls(**{k: self.carpent(v) for k, v in value.fields})
        if isinstance(value, dict):
            return {k: self.carpent(v) for k, v in value.items()}
        if isinstance(value, (list, tuple)):
            out = [self.carpent(v) for v in value]
            return type(value)(out) if isinstance(value, tuple) else out
        return value


_default_carpenter = ClassCarpenter()


def carpent(value):
    """Module-level convenience over a shared carpenter instance."""
    return _default_carpenter.carpent(value)
