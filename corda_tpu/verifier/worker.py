"""Out-of-process verification worker + the node-side service that feeds it.

Capability parity with the reference's verifier module (verifier/src/main/
kotlin/net/corda/verifier/Verifier.kt:49-94) and the node side
(node/.../transactions/OutOfProcessTransactionVerifierService.kt:20-71,
wire contract node-api/.../VerifierApi.kt:10-59):

- stateless workers consume ``verifier.requests`` from the durable broker,
  verify the carried transaction, reply to the request's reply queue, ack;
- N workers are competing consumers on one queue — the broker's
  visibility-timeout redelivery re-assigns un-acked work when a worker
  dies (the elasticity property VerifierTests.kt:55-113 proves);
- the node publishes requests tagged with a nonce and completes the
  matching future when the response arrives; responses are idempotent.

A worker verifies the full semantic package: every signature present and
required (minus the notary's during assembly) and the contract semantics
via ``LedgerTransaction.verify`` — signature math goes through the batched
device path when a device is available.
"""

from __future__ import annotations

import dataclasses
import logging
import random as _rand
import re
import threading
import time
from concurrent.futures import Future

from corda_tpu.ledger import LedgerTransaction, SignedTransaction
from corda_tpu.serialization import cbe_serializable, deserialize, serialize

logger = logging.getLogger(__name__)

VERIFICATION_REQUESTS_QUEUE = "verifier.requests"
VERIFICATION_RESPONSES_QUEUE_PREFIX = "verifier.responses."
# requests whose payload can't even name a reply queue land here for ops
# (the reference surfaces these only in the worker log; a queue lets the
# node count them — see DeadLetter)
VERIFICATION_DEAD_LETTER_QUEUE = "verifier.dead-letter"

# request msg_ids are "vreq-<reply_queue>-<nonce>[xattempt]"; the routing is
# recoverable from the id alone, so a worker can reply a structured error
# even when the payload is garbage (a CBE version skew between node and
# worker must degrade to an error reply, not a hung future)
_REQ_MSG_ID = re.compile(
    r"^vreq-(?P<reply>" + re.escape(VERIFICATION_RESPONSES_QUEUE_PREFIX)
    + r".+)-(?P<nonce>\d+)(?:x\d+)?$"
)


@cbe_serializable(name="verifier.Request")
@dataclasses.dataclass(frozen=True)
class VerificationRequest:
    """reference: VerifierApi.VerificationRequest (:17-38) — nonce, the
    transaction to verify, and where to reply. The signed form travels too
    so workers check signatures, not just contracts."""

    nonce: int
    stx: SignedTransaction
    ltx: LedgerTransaction
    reply_to: str


@cbe_serializable(name="verifier.Response")
@dataclasses.dataclass(frozen=True)
class VerificationResponse:
    """reference: VerifierApi.VerificationResponse (:40-58)."""

    nonce: int
    error: str = ""   # empty = verified


@cbe_serializable(name="verifier.DeadLetter")
@dataclasses.dataclass(frozen=True)
class DeadLetter:
    """A request the worker could neither process nor answer (payload
    undecodable AND msg_id unparseable): parked on the dead-letter queue
    with enough context for an operator to diagnose."""

    msg_id: str
    error: str
    payload: bytes


class VerifierWorker:
    """One stateless worker process/thread (reference: Verifier.main loop
    :66-84)."""

    def __init__(self, broker, use_device: bool = False,
                 worker_name: str = "verifier-worker"):
        self._broker = broker
        self._use_device = use_device
        self.name = worker_name
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.verified = 0
        self.failed = 0
        self.malformed = 0

    # ------------------------------------------------------------ serving
    def serve_one(self, timeout: float = 0.5) -> bool:
        """Consume and process one request; returns False on timeout."""
        msg = self._broker.consume(VERIFICATION_REQUESTS_QUEUE, timeout=timeout)
        if msg is None:
            return False
        try:
            req = deserialize(msg.payload)
            if not isinstance(req, VerificationRequest):
                raise TypeError(
                    f"expected VerificationRequest, got {type(req).__name__}"
                )
            error = self._verify(req)
        except Exception as e:
            # malformed request (e.g. node↔worker CBE version skew): the
            # node-side future must not hang. Routing is recoverable from
            # the msg_id even when the payload isn't — reply a structured
            # error; otherwise dead-letter for ops.
            logger.exception("malformed verification request")
            self.malformed += 1
            self._answer_malformed(msg, e)
            self._broker.ack(msg.msg_id)
            return True
        response = VerificationResponse(req.nonce, error)
        # reply THEN ack: a crash in between redelivers the request and the
        # node dedupes the duplicate response by nonce (at-least-once)
        self._broker.publish(
            req.reply_to, serialize(response),
            msg_id=f"vresp-{req.nonce}", sender=self.name,
        )
        self._broker.ack(msg.msg_id)
        if error:
            self.failed += 1
        else:
            self.verified += 1
        return True

    def _answer_malformed(self, msg, exc: Exception) -> None:
        m = _REQ_MSG_ID.match(msg.msg_id or "")
        if m is not None:
            self._broker.publish(
                m.group("reply"),
                serialize(VerificationResponse(
                    int(m.group("nonce")),
                    f"malformed request: {type(exc).__name__}: {exc}",
                )),
                msg_id=f"vresp-{m.group('nonce')}", sender=self.name,
            )
            return
        self._broker.publish(
            VERIFICATION_DEAD_LETTER_QUEUE,
            serialize(DeadLetter(
                msg.msg_id or "", f"{type(exc).__name__}: {exc}",
                bytes(msg.payload),
            )),
            msg_id=f"vdead-{msg.msg_id}", sender=self.name,
        )

    def _verify(self, req: VerificationRequest) -> str:
        try:
            # contract-only requests carry stx=None (CBE encodes None
            # natively); `0` is accepted for wire skew with pre-r5 writers
            # that punned the absent field as an int
            if req.stx is not None and req.stx != 0:
                from corda_tpu.verifier.batch import check_transactions

                report = check_transactions(
                    [req.stx],
                    [({req.ltx.notary.owning_key}
                      if req.ltx.notary is not None else set())],
                    use_device=self._use_device,
                )
                report.raise_first()
            req.ltx.verify()
            return ""
        except Exception as e:
            return f"{type(e).__name__}: {e}"

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "VerifierWorker":
        self._thread = threading.Thread(
            target=self._loop, name=self.name, daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        from corda_tpu.messaging.queue import QueueClosedError

        while not self._stop.is_set():
            try:
                self.serve_one()
            except (QueueClosedError, ConnectionError):
                return
            except Exception:
                logger.exception("verifier worker iteration failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


@dataclasses.dataclass
class _PendingRequest:
    future: Future
    payload: bytes           # the serialized request, for retry republish
    deadline: float
    attempts: int = 0        # republish count so far


class OutOfProcessVerifierService:
    """Node-side TransactionVerifierService publishing to the worker queue
    (reference: OutOfProcessTransactionVerifierService.kt — nonce→future
    map :32, response consumer :44-60, sendRequest :64-71).

    Every pending future carries a deadline: if no worker answers within
    ``request_timeout_s`` the request is republished under a fresh msg_id
    (up to ``max_retries`` times — covering a response lost to a worker
    crash after ack), then completed exceptionally. The broker's
    visibility-timeout redelivery handles workers that die mid-request, so
    the timeout should sit above the redelivery delay; this deadline is the
    backstop for everything redelivery can't see (no workers online for too
    long, poisoned responses, skew-dropped replies)."""

    def __init__(self, broker, node_name: str = "node",
                 request_timeout_s: float = 60.0, max_retries: int = 1):
        self._broker = broker
        self.reply_queue = VERIFICATION_RESPONSES_QUEUE_PREFIX + node_name
        self._request_timeout_s = request_timeout_s
        self._max_retries = max_retries
        self._lock = threading.Lock()
        self._pending: dict[int, _PendingRequest] = {}
        # run-unique nonce base: the broker's dedupe (msg_id → persistent
        # acked_ids) would silently drop a "vreq-...-1" republished by a
        # restarted node whose counter reset, spuriously timing out every
        # request up to the prior run's high-water mark
        self._nonce = (int(time.time() * 1e6) << 16) | _rand.getrandbits(16)
        self._sweep_interval_s = min(0.5, request_timeout_s / 4)
        self._last_sweep = 0.0
        self.timeouts = 0
        self.retries = 0
        self._stop = threading.Event()
        self._consumer = threading.Thread(
            target=self._consume_responses, name="verifier-responses",
            daemon=True,
        )
        self._consumer.start()

    def verify_stx(self, stx: SignedTransaction, resolve_state) -> Future:
        ltx = stx.tx.to_ledger_transaction(resolve_state)
        return self._submit(stx, ltx)

    def verify(self, ltx: LedgerTransaction) -> Future:
        """TransactionVerifierService face (contracts only, like the
        reference's LedgerTransaction-carrying requests)."""
        return self._submit(None, ltx)

    def _submit(self, stx, ltx) -> Future:
        fut: Future = Future()
        with self._lock:
            self._nonce += 1
            nonce = self._nonce
        payload = serialize(VerificationRequest(
            nonce, stx, ltx, self.reply_queue
        ))
        with self._lock:
            self._pending[nonce] = _PendingRequest(
                fut, payload, time.monotonic() + self._request_timeout_s
            )
        self._broker.publish(
            VERIFICATION_REQUESTS_QUEUE, payload,
            msg_id=f"vreq-{self.reply_queue}-{nonce}",
        )
        return fut

    def _consume_responses(self) -> None:
        from corda_tpu.messaging.queue import QueueClosedError

        while not self._stop.is_set():
            try:
                msg = self._broker.consume(self.reply_queue, timeout=0.5)
            except (QueueClosedError, ConnectionError):
                return
            # handle the response in hand BEFORE sweeping: a verdict that
            # arrives at deadline+ε must win over its own timeout
            if msg is not None:
                try:
                    resp = deserialize(msg.payload)
                    # validate before popping — a nonce-bearing poisoned
                    # reply must not orphan the future past the sweep
                    if not isinstance(resp, VerificationResponse):
                        raise TypeError(
                            f"expected VerificationResponse, "
                            f"got {type(resp).__name__}"
                        )
                    with self._lock:
                        entry = self._pending.pop(resp.nonce, None)
                    fut = entry.future if entry is not None else None
                    if fut is not None and not fut.done():
                        if resp.error:
                            fut.set_exception(
                                VerificationFailedError(resp.error)
                            )
                        else:
                            fut.set_result(None)
                except Exception:
                    logger.exception("bad verification response dropped")
                self._broker.ack(msg.msg_id)
            self._sweep_expired()

    def _sweep_expired(self) -> None:
        now = time.monotonic()
        if now - self._last_sweep < self._sweep_interval_s:
            return       # O(pending) locked scan; don't pay it per message
        self._last_sweep = now
        retry, fail = [], []
        with self._lock:
            for nonce, entry in self._pending.items():
                if now < entry.deadline:
                    continue
                if entry.attempts < self._max_retries:
                    entry.attempts += 1
                    entry.deadline = now + self._request_timeout_s
                    self.retries += 1
                    retry.append((nonce, entry))
                else:
                    fail.append(nonce)
            failed = [self._pending.pop(n) for n in fail]
            self.timeouts += len(fail)
        for nonce, entry in retry:
            # fresh msg_id (the x-suffix) so broker dedupe doesn't drop
            # the republish; responses stay idempotent by nonce
            self._broker.publish(
                VERIFICATION_REQUESTS_QUEUE, entry.payload,
                msg_id=f"vreq-{self.reply_queue}-{nonce}x{entry.attempts}",
            )
        for entry in failed:
            if not entry.future.done():
                entry.future.set_exception(VerificationTimeoutError(
                    f"no verification response within "
                    f"{self._request_timeout_s:g}s "
                    f"(after {self._max_retries} retries)"
                ))

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def shutdown(self) -> None:
        self._stop.set()
        # the sweep stops with the consumer thread: complete anything still
        # pending so no caller stays blocked in fut.result() past shutdown
        with self._lock:
            remaining = list(self._pending.values())
            self._pending.clear()
        for entry in remaining:
            if not entry.future.done():
                entry.future.set_exception(VerificationTimeoutError(
                    "verifier service shut down with the request pending"
                ))


class VerificationFailedError(Exception):
    pass


class VerificationTimeoutError(VerificationFailedError):
    """The out-of-process tier never answered: workers offline past the
    deadline, or the reply was lost/undeliverable (reference contract:
    VerifierApi.kt:40-58 — a response always carries the outcome; this
    is the node-side backstop when none arrives)."""


def run_worker(
    broker_path: str = "broker.db", use_device: bool = True,
    fabric_address: str | None = None, certs_dir: str | None = None,
    worker_name: str = "verifier-worker",
) -> None:
    """Process entry (reference: Verifier.main, Verifier.kt:49-87 — load
    config, open an authenticated connection TO THE NODE'S BROKER, consume
    verifier.requests). With ``fabric_address`` the worker is a certified
    fabric peer: its identity loads from ``certs_dir`` or (dev) is issued
    from the dev CA on the fly."""
    if fabric_address:
        from corda_tpu.messaging import SecureFabricClient
        from corda_tpu.node.certificates import issue_identity, load_identity

        if certs_dir:
            ident = load_identity(certs_dir)
        else:
            from corda_tpu.crypto import generate_keypair

            ident = issue_identity(
                f"O={worker_name},L=London,C=GB", generate_keypair()
            )
        broker = SecureFabricClient(
            fabric_address, ident.certificate, ident.keypair.private,
            ident.trust_root,
        )
    else:
        from corda_tpu.messaging import DurableQueueBroker

        broker = DurableQueueBroker(broker_path)
    worker = VerifierWorker(broker, use_device=use_device,
                            worker_name=worker_name)
    logger.info("verifier worker serving %s", VERIFICATION_REQUESTS_QUEUE)
    try:
        while True:
            worker.serve_one(timeout=1.0)
    except (KeyboardInterrupt, ConnectionError):
        pass


if __name__ == "__main__":
    import argparse

    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(prog="corda-tpu-verifier")
    ap.add_argument("broker", nargs="?", default="broker.db",
                    help="shared sqlite broker file (non-fabric mode)")
    ap.add_argument("--fabric", default=None, metavar="HOST:PORT",
                    help="connect to a node's secure broker as a "
                         "certified peer")
    ap.add_argument("--certs-dir", default=None,
                    help="identity.cbe/truststore.cbe directory "
                         "(defaults to a fresh dev-CA identity)")
    ap.add_argument("--name", default="verifier-worker")
    ap.add_argument("--no-device", action="store_true")
    a = ap.parse_args()
    run_worker(a.broker, use_device=not a.no_device,
               fabric_address=a.fabric, certs_dir=a.certs_dir,
               worker_name=a.name)
