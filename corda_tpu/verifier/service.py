"""Transaction verifier services.

Parity with the reference's two `TransactionVerifierService` impls
(node/.../services/transactions/InMemoryTransactionVerifierService.kt:11-14,
OutOfProcessTransactionVerifierService.kt:20-71) plus the TPU-native third
tier the north star calls for: a batching dispatcher that accumulates
concurrent verification requests and flushes them as one device batch
(signatures) + a host thread pool (contract semantics).

The batching window is the throughput/latency dial of SURVEY.md §7 hard
part (e): requests flush when either ``max_batch`` is reached or
``window_s`` elapses since the first queued request.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

from corda_tpu.ledger import LedgerTransaction, SignedTransaction


class VerificationError(Exception):
    pass


def _complete(future: Future, error: Exception | None = None) -> None:
    """Complete a future, tolerating caller-side cancellation (a cancelled
    future raising InvalidStateError must not abort completion of the rest
    of a batch)."""
    try:
        if error is None:
            future.set_result(None)
        else:
            future.set_exception(error)
    except Exception:
        pass


class TransactionVerifierService:
    """verify() returns a Future completing when verification finishes
    (reference: TransactionVerifierService.kt:10 returning CordaFuture)."""

    def verify(self, ltx: LedgerTransaction) -> Future:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class InMemoryVerifierService(TransactionVerifierService):
    """Host thread-pool verification — the reference's default 4-thread
    in-process service, kept as the no-device fallback and the baseline
    for bench comparisons."""

    def __init__(self, workers: int = 4):
        self._pool = ThreadPoolExecutor(max_workers=workers)

    def verify(self, ltx: LedgerTransaction) -> Future:
        return self._pool.submit(ltx.verify)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


class _Pending:
    __slots__ = ("stx", "resolve_state", "allowed_missing", "future")

    def __init__(self, stx, resolve_state, allowed_missing, future):
        self.stx = stx
        self.resolve_state = resolve_state
        self.allowed_missing = allowed_missing
        self.future = future


class BatchedVerifierService(TransactionVerifierService):
    """The TPU tier: concurrent verify requests accumulate; a flusher thread
    drains them into one scheme-bucketed device dispatch for every signature
    plus host-pool contract verification.

    ``verify_signed`` is the full-tx entry (signatures on device + contract
    semantics); ``verify`` keeps the reference's LedgerTransaction-only
    contract (semantics-only, host pool).
    """

    def __init__(
        self,
        *,
        max_batch: int = 4096,
        window_s: float = 0.005,
        workers: int = 8,
        use_device: bool = True,
    ):
        self._max_batch = max_batch
        self._window_s = window_s
        self._use_device = use_device
        self._pool = ThreadPoolExecutor(max_workers=workers)
        self._lock = threading.Condition()
        self._queue: list[_Pending] = []
        self._closed = False
        self._flusher = threading.Thread(
            target=self._flush_loop, name="verifier-flusher", daemon=True
        )
        self._flusher.start()
        self.stats = {"batches": 0, "txs": 0, "sigs": 0, "device_sigs": 0}

    # ------------------------------------------------------------- entries
    def verify(self, ltx: LedgerTransaction) -> Future:
        return self._pool.submit(ltx.verify)

    def verify_signed(
        self,
        stx: SignedTransaction,
        resolve_state=None,
        allowed_missing: set | None = None,
    ) -> Future:
        """Queue a full verification (device signature batch + host contract
        run when ``resolve_state`` is given). Completes with None or fails
        with the verification error."""
        fut: Future = Future()
        with self._lock:
            if self._closed:
                raise VerificationError("verifier service is shut down")
            self._queue.append(
                _Pending(stx, resolve_state, allowed_missing or set(), fut)
            )
            self._lock.notify()
        return fut

    # ------------------------------------------------------------- flusher
    def _flush_loop(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._closed:
                    self._lock.wait()
                if self._closed and not self._queue:
                    return
                # batch-accumulate: wait out the window from the first
                # arrival unless the batch is already full
                deadline = time.monotonic() + self._window_s
                while (
                    len(self._queue) < self._max_batch
                    and not self._closed
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._lock.wait(timeout=remaining)
                batch, self._queue = self._queue[: self._max_batch], self._queue[
                    self._max_batch :
                ]
            if batch:
                self._flush(batch)

    def _flush(self, batch: list[_Pending]) -> None:
        from .batch import check_transactions

        try:
            report = check_transactions(
                [p.stx for p in batch],
                [p.allowed_missing for p in batch],
                use_device=self._use_device,
            )
        except Exception as e:
            for p in batch:
                _complete(p.future, error=e)
            return
        self.stats["batches"] += 1
        self.stats["txs"] += len(batch)
        self.stats["sigs"] += report.n_sigs
        self.stats["device_sigs"] += report.n_device

        def finish(p: _Pending, sig_err):
            if sig_err is not None:
                _complete(p.future, error=sig_err)
                return
            try:
                if p.resolve_state is not None:
                    ltx = p.stx.tx.to_ledger_transaction(p.resolve_state)
                    ltx.verify()
                _complete(p.future)
            except Exception as e:
                _complete(p.future, error=e)

        for p, err in zip(batch, report.results):
            try:
                self._pool.submit(finish, p, err)
            except RuntimeError:
                # pool already shut down (service closing): finish inline so
                # no caller blocks on an unresolved future
                finish(p, err)

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()
        self._flusher.join()
        self._pool.shutdown(wait=True)
