"""Transaction verifier services.

Parity with the reference's two `TransactionVerifierService` impls
(node/.../services/transactions/InMemoryTransactionVerifierService.kt:11-14,
OutOfProcessTransactionVerifierService.kt:20-71) plus the TPU-native third
tier the north star calls for: a batching dispatcher that accumulates
concurrent verification requests and flushes them as one device batch
(signatures) + a host thread pool (contract semantics).

The batching window is the throughput/latency dial of SURVEY.md §7 hard
part (e): requests flush when either ``max_batch`` is reached or
``window_s`` elapses since the first queued request.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor

from corda_tpu.ledger import LedgerTransaction, SignedTransaction


class VerificationError(Exception):
    pass


def _complete(future: Future, error: Exception | None = None) -> None:
    """Complete a future, tolerating caller-side cancellation (a cancelled
    future raising InvalidStateError must not abort completion of the rest
    of a batch)."""
    try:
        if error is None:
            future.set_result(None)
        else:
            future.set_exception(error)
    except Exception:
        pass


class TransactionVerifierService:
    """verify() returns a Future completing when verification finishes
    (reference: TransactionVerifierService.kt:10 returning CordaFuture)."""

    def verify(self, ltx: LedgerTransaction) -> Future:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class InMemoryVerifierService(TransactionVerifierService):
    """Host thread-pool verification — the reference's default 4-thread
    in-process service, kept as the no-device fallback and the baseline
    for bench comparisons."""

    def __init__(self, workers: int = 4):
        self._pool = ThreadPoolExecutor(max_workers=workers)

    def verify(self, ltx: LedgerTransaction) -> Future:
        return self._pool.submit(ltx.verify)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


class _Pending:
    __slots__ = ("stx", "resolve_state", "allowed_missing", "future",
                 "arrived")

    def __init__(self, stx, resolve_state, allowed_missing, future):
        self.stx = stx
        self.resolve_state = resolve_state
        self.allowed_missing = allowed_missing
        self.future = future
        # first-arrival timestamp: the flush window is owed from HERE even
        # when the item is sliced off beyond max_batch and carried into a
        # later flush decision (the leftover-aging fix)
        self.arrived = time.monotonic()


class BatchedVerifierService(TransactionVerifierService):
    """The TPU tier. By default (``use_scheduler=True``) every
    ``verify_signed`` submits straight into the process-global serving
    scheduler (corda_tpu/serving): coalescing with OTHER clients (notary
    windows, flow verifies) happens there with continuous batching, so a
    lone request on an idle device dispatches immediately instead of
    waiting out ``window_s``, and sustained load still forms full device
    batches. Contract semantics run on this service's host pool once the
    signature verdicts land.

    ``use_scheduler=False`` keeps the self-contained windowed flusher
    (the pre-serving design): requests accumulate and flush as one
    scheme-bucketed dispatch when ``max_batch`` fills or ``window_s``
    elapses since the OLDEST pending request's arrival — the window ages
    with items carried over past a full batch, it never restarts for
    leftovers.

    ``verify_signed`` is the full-tx entry (signatures on device + contract
    semantics); ``verify`` keeps the reference's LedgerTransaction-only
    contract (semantics-only, host pool).
    """

    def __init__(
        self,
        *,
        max_batch: int = 4096,
        window_s: float = 0.005,
        workers: int = 8,
        use_device: bool = True,
        use_scheduler: bool = True,
    ):
        self._max_batch = max_batch
        self._window_s = window_s
        self._use_device = use_device
        self._use_scheduler = use_scheduler
        self._pool = ThreadPoolExecutor(max_workers=workers)
        self._lock = threading.Condition()
        self._queue: list[_Pending] = []
        self._closed = False
        self._outstanding: set[Future] = set()   # scheduler-routed futures
        # bounded recent-batch dedupe for stats["batches"]: seqs arrive
        # (nearly) in order, so a small window suffices; an unbounded set
        # would grow one int per device batch for the service's lifetime
        self._batch_seqs: set[int] = set()
        self._batch_seq_order: "deque[int]" = deque(maxlen=4096)
        self._flusher: threading.Thread | None = None
        if not use_scheduler:
            self._flusher = threading.Thread(
                target=self._flush_loop, name="verifier-flusher", daemon=True
            )
            self._flusher.start()
        self.stats = {"batches": 0, "txs": 0, "sigs": 0, "device_sigs": 0}

    @property
    def use_device(self) -> bool:
        return self._use_device

    @property
    def routes_via_scheduler(self) -> bool:
        return self._use_scheduler

    # ------------------------------------------------------------- entries
    def verify(self, ltx: LedgerTransaction) -> Future:
        return self._pool.submit(ltx.verify)

    def verify_signed(
        self,
        stx: SignedTransaction,
        resolve_state=None,
        allowed_missing: set | None = None,
    ) -> Future:
        """Queue a full verification (device signature batch + host contract
        run when ``resolve_state`` is given). Completes with None or fails
        with the verification error. Admission-control rejects from the
        serving scheduler (bounded queue) propagate synchronously."""
        if self._use_scheduler:
            return self._submit_via_scheduler(
                stx, resolve_state, allowed_missing or set()
            )
        fut: Future = Future()
        with self._lock:
            if self._closed:
                raise VerificationError("verifier service is shut down")
            self._queue.append(
                _Pending(stx, resolve_state, allowed_missing or set(), fut)
            )
            self._lock.notify()
        return fut

    # -------------------------------------------------- scheduler routing
    def _submit_via_scheduler(self, stx, resolve_state, allowed) -> Future:
        from corda_tpu.observability import SPAN_VERIFIER_REQUEST, tracer
        from corda_tpu.serving import SERVICE, device_scheduler

        with self._lock:
            if self._closed:
                raise VerificationError("verifier service is shut down")
            fut: Future = Future()
            self._outstanding.add(fut)
        # verifier.request spans the whole round-trip (submit → scheduler
        # queue → batch → contract run); the caller's context is captured
        # HERE because settle/finish run on scheduler and pool threads
        trc = tracer()
        span = trc.start(SPAN_VERIFIER_REQUEST, trc.current(),
                         attrs={"tx.id": str(stx.id)})
        t0 = time.monotonic()
        try:
            inner = device_scheduler().submit_transactions(
                [stx], [allowed], priority=SERVICE,
                use_device=self._use_device, trace=span,
            )
        except Exception as e:
            span.set_error(e)
            span.finish()
            with self._lock:
                self._outstanding.discard(fut)
            raise

        def settle(f: Future):
            try:
                report = f.result()
                with self._lock:
                    self.stats["txs"] += 1
                    self.stats["sigs"] += report.n_sigs
                    self.stats["device_sigs"] += report.n_device
                    if report.batch_seq is not None:
                        # distinct device batches this service's requests
                        # landed in — comparable to the old per-flush count
                        if report.batch_seq not in self._batch_seqs:
                            if (len(self._batch_seq_order)
                                    == self._batch_seq_order.maxlen):
                                self._batch_seqs.discard(
                                    self._batch_seq_order[0]
                                )
                            self._batch_seq_order.append(report.batch_seq)
                            self._batch_seqs.add(report.batch_seq)
                            self.stats["batches"] += 1
                err = report.results[0]
            except Exception as e:
                err = e

            def finish():
                try:
                    if err is not None:
                        span.set_error(err)
                        _complete(fut, error=err)
                    elif resolve_state is not None:
                        ltx = stx.tx.to_ledger_transaction(resolve_state)
                        ltx.verify()
                        _complete(fut)
                    else:
                        _complete(fut)
                except Exception as e:
                    span.set_error(e)
                    _complete(fut, error=e)
                finally:
                    span.finish()
                    # verify_signed round-trip (queue + batch + contract
                    # run) — the verifier-tier latency distribution the
                    # exposition reports p50/p95/p99 for
                    from corda_tpu.node.monitoring import node_metrics

                    node_metrics().timer("verifier.request_s").update(
                        time.monotonic() - t0
                    )
                    with self._lock:
                        self._outstanding.discard(fut)

            try:
                self._pool.submit(finish)
            except RuntimeError:
                finish()  # pool already shut down: finish inline

        inner.add_done_callback(settle)
        return fut

    # ------------------------------------------------------------- flusher
    def _flush_loop(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._closed:
                    self._lock.wait()
                if self._closed and not self._queue:
                    return
                # batch-accumulate: the window is owed from the OLDEST
                # pending item's arrival (which may predate this loop
                # iteration when leftovers were sliced off a full batch),
                # so no request waits more than window_s beyond a free slot
                deadline = self._queue[0].arrived + self._window_s
                while (
                    len(self._queue) < self._max_batch
                    and not self._closed
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._lock.wait(timeout=remaining)
                batch, self._queue = self._queue[: self._max_batch], self._queue[
                    self._max_batch :
                ]
            if batch:
                self._flush(batch)

    def _flush(self, batch: list[_Pending]) -> None:
        from .batch import check_transactions

        try:
            report = check_transactions(
                [p.stx for p in batch],
                [p.allowed_missing for p in batch],
                use_device=self._use_device,
            )
        except Exception as e:
            for p in batch:
                _complete(p.future, error=e)
            return
        # same lock the scheduler-routed settle callbacks take: stats is
        # one surface whichever route served the batch
        with self._lock:
            self.stats["batches"] += 1
            self.stats["txs"] += len(batch)
            self.stats["sigs"] += report.n_sigs
            self.stats["device_sigs"] += report.n_device

        def finish(p: _Pending, sig_err):
            if sig_err is not None:
                _complete(p.future, error=sig_err)
                return
            try:
                if p.resolve_state is not None:
                    ltx = p.stx.tx.to_ledger_transaction(p.resolve_state)
                    ltx.verify()
                _complete(p.future)
            except Exception as e:
                _complete(p.future, error=e)

        for p, err in zip(batch, report.results):
            try:
                self._pool.submit(finish, p, err)
            except RuntimeError:
                # pool already shut down (service closing): finish inline so
                # no caller blocks on an unresolved future
                finish(p, err)

    def shutdown(self) -> None:
        """Stop accepting work; every queued and in-flight future completes
        (result or error) before this returns. Idempotent — a second
        shutdown is a no-op."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            outstanding = list(self._outstanding)
            self._lock.notify_all()
        if self._flusher is not None:
            self._flusher.join()
        if outstanding:
            import concurrent.futures as _cf

            _cf.wait(outstanding, timeout=60)
        self._pool.shutdown(wait=True)
