"""Scheme-bucketed batch signature verification.

The core throughput idea of the framework (BASELINE.json north star): the
reference verifies one signature per JCA call inside a per-transaction loop
(TransactionWithSignatures.kt:63 → Crypto.doVerify, Crypto.kt:552-555,
621-624). Here the (key, signature, message) rows of *many* transactions are
flattened, bucketed by scheme id — mirroring the dispatch switch of
Crypto.findSignatureScheme (Crypto.kt:236-267) — and each bucket goes to its
best engine in one shot:

  scheme 4 (ed25519)  → full shape-bucketed batches: ONE algebraic
                        RLC batch check (batchverify/rlc.py, default —
                        CORDA_TPU_BATCH_RLC, docs/BATCH_VERIFY.md);
                        partial batches: the batched device kernel
                        (ops/ed25519.py)
  schemes 2/3 (ECDSA) → batched windowed ladder (ops/secp256.py / _pallas)
  scheme 5 (SPHINCS)  → batched hash-chain sweep (ops/sphincs_batch.py)
                        on accelerator backends; host loop on CPU
  scheme 1 (RSA — cold path) → host loop

Bucketing + padding policy is what decides real MXU utilization (SURVEY.md
§7 hard part (a)): the ed25519 path pads to power-of-two buckets so XLA
compiles once per bucket.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from corda_tpu.crypto import (
    ECDSA_SECP256K1_SHA256,
    ECDSA_SECP256R1_SHA256,
    EDDSA_ED25519_SHA512,
    SPHINCS256_SHA256,
    CryptoError,
    SecureHash,
    TransactionSignature,
    is_fulfilled_by,
    is_valid,
)
from corda_tpu.ledger import SignedTransaction
from corda_tpu.ledger.signed import SignaturesMissingException

# Schemes with a batched device kernel (ops/ed25519.py, ops/secp256.py).
_DEVICE_SCHEMES = {
    EDDSA_ED25519_SHA512,
    ECDSA_SECP256K1_SHA256,
    ECDSA_SECP256R1_SHA256,
}


def _effective_device_schemes(use_device: bool) -> set:
    """The device-capable scheme set for this dispatch. SPHINCS batches on
    device too (pure hashing, ops/sphincs_batch.py) on accelerator
    backends: since r5 the whole FORS+hypertree walk is ONE fused jit —
    one dispatch, one link round trip — so it survives a tunneled link
    (the r4 eager chain was ~100 sequential queue-drain round trips and
    collapsed the mixed bench to 0.04× host, which is why it used to be
    host-pinned by measured RTT). The XLA:CPU test tier still runs the
    host loop (the fused graph is a CPU compile tarpit) unless the
    documented CORDA_TPU_SPHINCS=device override forces the device path —
    the override outranks the backend gate (it exists precisely to pin
    routing on non-TPU accelerator backends). Only consulted when
    ``use_device`` — host-only callers never touch (or initialize) jax."""
    if not use_device:
        return set()
    schemes = set(_DEVICE_SCHEMES)
    forced = _sphincs_override()
    if forced == "device":
        # the override outranks (and never consults) the backend gate
        schemes.add(SPHINCS256_SHA256)
        return schemes
    if forced != "host":
        import jax

        if jax.default_backend() == "tpu":
            schemes.add(SPHINCS256_SHA256)
    return schemes


def _sphincs_override() -> str:
    """The CORDA_TPU_SPHINCS routing override: "device", "host", or ""
    (no override — route by backend)."""
    import os

    forced = os.environ.get("CORDA_TPU_SPHINCS", "").strip().lower()
    return forced if forced in ("device", "host") else ""


class PendingRows:
    """An in-flight row verification: device buckets are ENQUEUED (async JAX
    dispatch, no readback yet), host buckets already resolved. ``collect()``
    materializes the (N,) mask with one blocking readback per device bucket.

    The two-phase split is what lets callers (the pipelined notary, the
    verifier service queue loop) overlap the device ladder time — dominated
    by the tunneled interconnect's ~100 ms round trip — with host work on a
    previous batch.

    Degradation contract: a device bucket whose READBACK fails (device
    reset, link loss, injected fault) re-verifies on the host reference
    path via the fallback closure stored with it — the batch always
    completes, and the failover is counted in the process metrics
    (``verifier.device_failover``). ``device_rows`` reflects where rows
    actually settled, so downstream routing decisions (the notary's
    response-sign tiering) track reality rather than intent.
    """

    __slots__ = ("_n", "_deferred", "_out", "device_rows", "device_mask",
                 "padded_lanes", "stall_until")

    def __init__(self, n: int):
        self._n = n
        self._deferred: list[tuple[list[int], object, object]] = []
        self._out = np.zeros(n, dtype=bool)
        self.device_rows = 0
        # per-row attribution of where the verdict settled (the serving
        # scheduler slices coalesced multi-client batches back apart and
        # needs per-request device counts, not just the batch total)
        self.device_mask = np.zeros(n, dtype=bool)
        # total padded lanes the device ACTUALLY ran across scheme buckets
        # (each bucket pads independently) — the ground truth behind the
        # scheduler's pad-waste/fill-ratio accounting; 0 for host-only
        self.padded_lanes = 0
        # injected-stall horizon (faultinject): until this monotonic time
        # the batch reports not-ready and collect() waits it out — a sick
        # device that computes, just far too slowly. None = no stall.
        self.stall_until: float | None = None

    def inject_stall(self, delay_s: float) -> None:
        """Graft a deterministic stall onto this dispatch (the
        ``stall_sites`` fault mode): the batch stays genuinely in flight
        and not-ready for ``delay_s`` — the shape the scheduler's hedge
        path must survive. Stalls from several sites compound to the
        furthest horizon."""
        if delay_s <= 0:
            return
        horizon = time.monotonic() + delay_s
        self.stall_until = max(self.stall_until or 0.0, horizon)

    def ready(self) -> bool:
        """Non-blocking: True when every enqueued device bucket has
        finished computing, i.e. ``collect()`` would not block on the
        device. Completion-order collectors (the serving scheduler's
        settle loop) poll this to harvest whichever in-flight batch lands
        first."""
        from corda_tpu.ops._blockpack import result_ready

        if self.stall_until is not None and \
                time.monotonic() < self.stall_until:
            return False
        return all(result_ready(mask) for _idxs, mask, _fb in self._deferred)

    def collect(self) -> np.ndarray:
        # settle scheme buckets in COMPLETION order, not dispatch order: a
        # mixed batch enqueues e.g. the ed25519 bucket before the slower
        # ECDSA ladder, but whichever bucket finishes first should pay its
        # host copy-out while the others are still computing — blocking on
        # the first-dispatched bucket would stack the readbacks serially
        # behind the slowest one. When nothing is ready yet, block on the
        # oldest dispatch (the FIFO degenerate case).
        from corda_tpu.ops._blockpack import result_ready

        if self.stall_until is not None:
            wait = self.stall_until - time.monotonic()
            if wait > 0:
                time.sleep(wait)  # the injected device stall, served here
            self.stall_until = None
        deferred, self._deferred = self._deferred, []
        while deferred:
            entry = next(
                (e for e in deferred if result_ready(e[1])), deferred[0]
            )
            deferred.remove(entry)
            idxs, mask, fallback = entry
            try:
                self._out[idxs] = np.asarray(mask)[: len(idxs)]
            except Exception:
                _note_device_failover(len(idxs), "collect")
                self.device_rows -= len(idxs)
                self.device_mask[idxs] = False
                fallback()
        return self._out


def _note_device_failover(n_rows: int, stage: str) -> None:
    """Record a device→host failover in the process metrics (the counters
    the chaos acceptance criteria assert on)."""
    import logging

    from corda_tpu.node.monitoring import node_metrics

    node_metrics().counter("verifier.device_failover").inc()
    node_metrics().counter("verifier.device_failover_rows").inc(n_rows)
    logging.getLogger(__name__).warning(
        "device verification failed at %s; %d rows fell back to the host "
        "reference path", stage, n_rows,
    )


def dispatch_signature_rows(
    rows: list[tuple], *, use_device: bool = True,
    min_bucket: int | None = None, device=None,
) -> PendingRows:
    """Enqueue verification of (PublicKey, signature, message) rows.

    One async device dispatch per device-capable scheme bucket; host loop
    (resolved immediately) for the rest. Row order is preserved in the
    collected mask. ``min_bucket`` pins the device pad-bucket floor (one
    compiled kernel shape for services with ragged batch sizes).
    ``device`` pins every device bucket to one specific ``jax.Device``
    (the mesh-striped scheduler and per-ordinal canary probes place work
    explicitly); ``None`` keeps the backend default / service-mesh
    routing.
    """
    n = len(rows)
    pending = PendingRows(n)
    if n == 0:
        return pending

    buckets: dict[int, list[int]] = {}
    for i, (key, _sig, _msg) in enumerate(rows):
        buckets.setdefault(key.scheme_id, []).append(i)

    device_schemes = _effective_device_schemes(use_device)
    for scheme_id, idxs in buckets.items():
        if scheme_id == EDDSA_ED25519_SHA512 and \
                _rlc_bucket_eligible(idxs, min_bucket):
            # full shape-bucketed ed25519 batches settle algebraically:
            # one RLC multi-scalar multiplication instead of len(idxs)
            # independent verifies (docs/BATCH_VERIFY.md). Resolved
            # eagerly like any host bucket; the device hedge/per-sig
            # resilience paths in the scheduler are untouched.
            _rlc_verify_bucket(pending, rows, idxs)
        elif scheme_id in device_schemes:
            try:
                _dispatch_device_bucket(
                    pending, rows, scheme_id, idxs, min_bucket,
                    device=device,
                )
            except Exception:
                # graceful degradation: a device bucket that fails to
                # DISPATCH (backend gone, kernel error, injected fault)
                # completes on the host reference path instead of failing
                # the whole batch — the notary/verifier keeps serving
                # while the operator reads the failover counters
                _note_device_failover(len(idxs), "dispatch")
                _host_verify_bucket(pending, rows, idxs)
        else:
            _host_verify_bucket(pending, rows, idxs)
    return pending


def _host_verify_bucket(pending: PendingRows, rows, idxs) -> None:
    for i in idxs:
        key, sig, msg = rows[i]
        pending._out[i] = is_valid(key, sig, msg)


def _rlc_bucket_eligible(idxs, min_bucket) -> bool:
    """RLC settles FULL shape-bucketed ed25519 batches only: a
    ``min_bucket`` floor marks a scheduler-shaped dispatch, and a bucket
    at or above the floor amortizes the MSM's fixed doubling chain.
    Partial batches keep the pre-RLC engines (device kernel or host
    loop), as do opted-out deployments (CORDA_TPU_BATCH_RLC=0)."""
    if min_bucket is None or len(idxs) < min_bucket:
        return False
    from corda_tpu.batchverify import rlc_enabled

    return rlc_enabled()


def _rlc_verify_bucket(pending: PendingRows, rows, idxs) -> None:
    """Settle one ed25519 bucket through the RLC batch check. Degradation
    contract matches the device buckets: ANY failure of the algebraic
    path — including an injected fault at ``batchverify.msm`` — lands
    every row on the host per-signature reference path, so no future is
    ever lost to the optimization."""
    from corda_tpu.batchverify import verify_batch_rlc

    entries = [(rows[i][0].encoded, rows[i][1], rows[i][2]) for i in idxs]
    try:
        verdicts = verify_batch_rlc(entries)
    except Exception:
        import logging

        from corda_tpu.node.monitoring import node_metrics

        node_metrics().counter("batchverify.msm_faults").inc()
        logging.getLogger(__name__).warning(
            "RLC batch verification failed; %d rows fell back to the "
            "host per-signature path", len(idxs),
        )
        _host_verify_bucket(pending, rows, idxs)
        return
    for i, ok in zip(idxs, verdicts):
        pending._out[i] = ok


def _dispatch_device_bucket(
    pending: PendingRows, rows, scheme_id: int, idxs, min_bucket,
    device=None,
) -> None:
    """Enqueue one scheme bucket on device; raises on dispatch failure
    (the caller degrades to host). The faultinject site lets a seeded
    chaos plan force exactly this failure — or an injected STALL, which
    grafts onto the pending so the bucket computes but stays not-ready
    for the delay (the batch stalls in flight, the dispatcher does not
    block). An explicit ``device`` pins the bucket to that chip and
    bypasses service-mesh routing — the striped scheduler has already
    made the placement decision."""
    import contextlib

    from corda_tpu.faultinject import check_site

    stall_s = check_site("verifier.device")
    keys = [rows[i][0].encoded for i in idxs]
    sigs = [rows[i][1] for i in idxs]
    msgs = [rows[i][2] for i in idxs]
    from corda_tpu.ops._blockpack import start_host_copy
    from corda_tpu.parallel.mesh import service_mesh_active

    # production fan-out: shard EVERY device-capable bucket over
    # the device mesh (SURVEY §2.9 P3) — the reference's fan-out
    # load-balances all verification work across workers
    # (Verifier.kt:66-84), not one scheme. Single chip degrades
    # transparently to the plain batched dispatches below. A pinned
    # ``device`` means the scheduler already striped this bucket onto
    # one chip: no second fan-out.
    on_mesh = device is None and service_mesh_active()
    if on_mesh:
        from corda_tpu.parallel.mesh import service_mesh_verifier

        mesh_v = service_mesh_verifier()
    if device is not None:
        import jax

        pin = jax.default_device(device)
    else:
        pin = contextlib.nullcontext()
    with pin:
        if scheme_id == EDDSA_ED25519_SHA512:
            if on_mesh:
                mask, _spent, _total = mesh_v.dispatch_rows(
                    keys, sigs, msgs, min_bucket=min_bucket
                )
            else:
                from corda_tpu.ops.ed25519 import ed25519_verify_dispatch

                mask = ed25519_verify_dispatch(
                    keys, sigs, msgs, min_bucket=min_bucket
                )
        elif scheme_id == SPHINCS256_SHA256:
            if on_mesh:
                mask = mesh_v.dispatch_sphincs_rows(
                    keys, sigs, msgs, min_bucket=min_bucket
                )
            else:
                from corda_tpu.ops.sphincs_batch import (
                    sphincs_verify_dispatch,
                )

                mask = sphincs_verify_dispatch(
                    keys, sigs, msgs, min_bucket=min_bucket
                )
        else:
            # async like the ed25519 bucket: the ECDSA ladder queues on
            # device and collects later, so mixed-scheme batches overlap
            # both ladders instead of serializing on this one (r2
            # VERDICT weak #2)
            curve = (
                "secp256k1"
                if scheme_id == ECDSA_SECP256K1_SHA256
                else "secp256r1"
            )
            if on_mesh:
                mask = mesh_v.dispatch_ecdsa_rows(
                    curve, keys, sigs, msgs, min_bucket=min_bucket
                )
            else:
                from corda_tpu.ops.secp256 import ecdsa_verify_dispatch

                mask = ecdsa_verify_dispatch(
                    curve, keys, sigs, msgs, min_bucket=min_bucket
                )
    start_host_copy(mask)
    pending._deferred.append(
        (idxs, mask, lambda: _host_verify_bucket(pending, rows, idxs))
    )
    pending.device_rows += len(idxs)
    pending.device_mask[idxs] = True
    # the returned mask is bucket-padded: its leading dim is the lane
    # count this scheme bucket really occupied on device
    shape = getattr(mask, "shape", None)
    pending.padded_lanes += int(shape[0]) if shape else len(idxs)
    if stall_s:
        pending.inject_stall(stall_s)


def verify_signature_rows(
    rows: list[tuple], *, use_device: bool = True
) -> np.ndarray:
    """Verify (PublicKey, signature, message) rows → (N,) bool mask.

    Synchronous wrapper over ``dispatch_signature_rows``.
    """
    return dispatch_signature_rows(rows, use_device=use_device).collect()


@dataclasses.dataclass
class BatchVerifyReport:
    """Per-transaction outcome of a batched signature check."""

    results: list  # Exception | None per transaction (None = ok)
    n_sigs: int
    n_device: int
    # device-batch sequence number when the check went through the serving
    # scheduler (requests coalesced into one device batch share it); None
    # on the direct dispatch path
    batch_seq: int | None = None
    # device ordinal the scheduler batch ran on (None when host-settled
    # or on the direct dispatch path) — per-chip attribution
    device: int | None = None

    @property
    def ok(self) -> bool:
        return all(r is None for r in self.results)

    def raise_first(self) -> None:
        for r in self.results:
            if r is not None:
                raise r


class InvalidSignatureError(CryptoError):
    """A signature failed batch verification. A CryptoError subclass so
    callers catching the direct path's per-signature failure
    (``TransactionSignature.verify`` → CryptoError) see the same
    hierarchy whichever verifier tier served the check."""

    def __init__(self, tx_id: SecureHash, sig: TransactionSignature):
        self.tx_id = tx_id
        self.sig = sig
        super().__init__(f"invalid signature by {sig.by!r} on tx {tx_id}")


class PendingTxCheck:
    """An in-flight ``check_transactions``: signature rows are enqueued on
    device, the per-tx signer-set algebra runs at ``collect()`` time."""

    __slots__ = ("_stxs", "_allowed", "_pending", "_row_tx", "_row_sig",
                 "_n_device")

    def __init__(self, stxs, allowed, pending, row_tx, row_sig, n_device):
        self._stxs = stxs
        self._allowed = allowed
        self._pending = pending
        self._row_tx = row_tx
        self._row_sig = row_sig
        self._n_device = n_device

    def collect(self) -> BatchVerifyReport:
        mask = self._pending.collect()
        # a collect-time failover shrinks the pending's device count; the
        # report reflects where the rows actually settled
        return tx_report_from_mask(
            self._stxs, self._allowed, mask, self._row_tx, self._row_sig,
            min(self._n_device, self._pending.device_rows),
        )


def flatten_signature_rows(stxs: list[SignedTransaction]):
    """Flatten many transactions' signature triples into one row list plus
    the row→(tx, sig) back-maps — the feed shape of every bucketed
    dispatch (direct or through the serving scheduler)."""
    rows: list[tuple] = []
    row_tx: list[int] = []
    row_sig: list[int] = []
    for t, stx in enumerate(stxs):
        for j, (key, sig, msg) in enumerate(stx.signature_triples()):
            rows.append((key, sig, msg))
            row_tx.append(t)
            row_sig.append(j)
    return rows, row_tx, row_sig


def tx_report_from_mask(
    stxs, allowed, mask, row_tx, row_sig, n_device, batch_seq=None,
    device=None,
) -> BatchVerifyReport:
    """The per-transaction signer-set algebra over a row verdict mask —
    shared by the direct path (``PendingTxCheck``) and the serving
    scheduler so both produce identical reports by construction."""
    results: list = [None] * len(stxs)
    # first invalid signature per tx wins (matches the sequential
    # reference loop's first-throw behavior)
    for i, valid in enumerate(mask):
        t = row_tx[i]
        if not valid and results[t] is None:
            results[t] = InvalidSignatureError(
                stxs[t].id, stxs[t].sigs[row_sig[i]]
            )
    for t, stx in enumerate(stxs):
        if results[t] is not None:
            continue
        signed_by = {s.by for s in stx.sigs}
        missing = {
            k
            for k in stx.required_signing_keys
            if not is_fulfilled_by(k, signed_by)
        } - set(allowed[t])
        if missing:
            results[t] = SignaturesMissingException(missing, stx.id)
    return BatchVerifyReport(
        results, n_sigs=len(row_tx), n_device=n_device, batch_seq=batch_seq,
        device=device,
    )


def dispatch_transactions(
    stxs: list[SignedTransaction],
    allowed_missing: list[set] | None = None,
    *,
    use_device: bool = True,
    min_bucket: int | None = None,
) -> PendingTxCheck:
    """Enqueue the signature half of a batched tx check; see
    ``check_transactions`` for semantics."""
    if allowed_missing is None:
        allowed_missing = [set()] * len(stxs)
    if len(allowed_missing) != len(stxs):
        raise ValueError("allowed_missing length mismatch")

    rows, row_tx, row_sig = flatten_signature_rows(stxs)

    pending = dispatch_signature_rows(
        rows, use_device=use_device, min_bucket=min_bucket
    )
    return PendingTxCheck(
        stxs, allowed_missing, pending, row_tx, row_sig, pending.device_rows
    )


def check_transactions(
    stxs: list[SignedTransaction],
    allowed_missing: list[set] | None = None,
    *,
    use_device: bool = True,
) -> BatchVerifyReport:
    """Batched equivalent of ``stx.verify_signatures_except(allowed)`` over
    many transactions: all signature rows flatten into one scheme-bucketed
    dispatch, then per-tx signer-set algebra (composite-key fulfilment, the
    host-cheap half of TransactionWithSignatures.kt:29-63) runs on the mask.
    """
    return dispatch_transactions(
        stxs, allowed_missing, use_device=use_device
    ).collect()
