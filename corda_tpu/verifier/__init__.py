"""Verification engine — layer 8 (SURVEY.md §2.4, §3.3).

The TPU-native re-design of the reference's verification tier: instead of a
4-thread in-process pool (InMemoryTransactionVerifierService.kt:11-14) or
N competing JVM worker processes (Verifier.kt:49-87), signature checks from
many transactions are flattened into scheme-bucketed device batches
(`batch.py`), contract semantics run on host, and whole back-chain DAGs
verify as topological wavefronts (`corda_tpu.parallel.wavefront`).
"""

from .batch import (
    BatchVerifyReport,
    PendingTxCheck,
    check_transactions,
    dispatch_signature_rows,
    dispatch_transactions,
    verify_signature_rows,
)
from .service import (
    BatchedVerifierService,
    InMemoryVerifierService,
    TransactionVerifierService,
    VerificationError,
)
from .worker import (
    OutOfProcessVerifierService,
    VerificationFailedError,
    VerifierWorker,
)

__all__ = [
    "BatchVerifyReport",
    "PendingTxCheck",
    "check_transactions",
    "dispatch_signature_rows",
    "dispatch_transactions",
    "verify_signature_rows",
    "BatchedVerifierService",
    "InMemoryVerifierService",
    "TransactionVerifierService",
    "VerificationError",
    "OutOfProcessVerifierService", "VerificationFailedError",
    "VerifierWorker",
]
