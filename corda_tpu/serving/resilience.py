"""Self-healing dispatch policy: quarantine, hedging, circuit breaking.

PR 7 built the per-device watchdog (``DeviceWatchdog.unhealthy_ordinals``
is "the read the future mesh scheduler consults") — and nothing consumed
it: a flagged device kept receiving traffic, a dispatch that *stalled*
(rather than raised) parked its batch in flight forever holding a depth
slot, and the scheduler's only failure handling was a one-shot
whole-batch host failover. This module is the policy layer the
``DeviceScheduler`` consults on every dispatch and settle — the
machinery the mesh scheduler (ROADMAP item 1) will instantiate
per-ordinal. Four mechanisms:

1. **Quarantine state machine** per device ordinal::

       HEALTHY ──strike──▶ SUSPECT ──K strikes──▶ QUARANTINED
          ▲                   │                        │ backoff
          │  probe verdicts   │ clean settle           ▼ elapsed
          └─────────────── PROBATION ◀── canary probe dispatched

   Strikes come from dispatch failures, fired hedges (stall evidence)
   and watchdog ``device.unhealthy`` events (the devicemon subscription
   hook). A quarantined ordinal receives NO scheduler traffic; it is
   re-admitted only through exponential-backoff **canary probes** — a
   known-answer signature batch (valid rows plus a tampered one) whose
   verdicts must match exactly, AND must have settled on device (a probe
   that silently failed over to host proves nothing). Quarantine entry
   writes one flight-recorder dump per episode.

2. **Hedged dispatch deadlines**: every in-flight device batch gets a
   deadline — execute-wall EWMA (devicemon when on, else the scheduler's
   own latency EWMA) × ``CORDA_TPU_HEDGE_FACTOR`` — and on expiry the
   scheduler re-runs the batch on the host reference path, first result
   wins, each future completed exactly once, the loser's late readback
   discarded. The deadline logic lives here; the firing lives in the
   scheduler's hedge thread.

3. **Circuit breaker** over the whole device tier: K consecutive device
   failures / hedge losses trip it OPEN (all traffic host-routed, zero
   device enqueues), exponential-backoff HALF_OPEN canary probes close
   it again.

4. **Deterministic re-dispatch** (scheduler side, policy-gated): a batch
   whose device dispatch failed re-enters the queue with its original
   arrival times and priority instead of silently failing over —
   verification is pure so re-execution is safe; futures are not, so the
   scheduler pins single completion under hedge/settle races.

Off by default: construct a ``ResiliencePolicy`` and pass it to
``DeviceScheduler(resilience=…)``, or set ``CORDA_TPU_RESILIENCE=1`` for
the default policy on every scheduler. Counters live under
``serving.quarantine.*`` / ``serving.hedge.*`` / ``serving.breaker.*``
(docs/OBSERVABILITY.md); state is surfaced in ``monitoring_snapshot()``
(``resilience`` section) and every flight dump.
"""

from __future__ import annotations

import os
import threading
import time

# ------------------------------------------------------- quarantine states

HEALTHY = "healthy"
SUSPECT = "suspect"            # struck, still serving traffic
QUARANTINED = "quarantined"    # evicted; waiting out the probe backoff
PROBATION = "probation"        # canary probe in flight

# ----------------------------------------------------------- breaker states

BREAKER_CLOSED = 0
BREAKER_OPEN = 1
BREAKER_HALF_OPEN = 2


def _metrics():
    from corda_tpu.node.monitoring import node_metrics

    return node_metrics()


def _env_float(name: str, default: float) -> float:
    try:
        raw = os.environ.get(name, "").strip()
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        raw = os.environ.get(name, "").strip()
        return int(raw) if raw else default
    except ValueError:
        return default


class _OrdinalHealth:
    """Per-ordinal quarantine bookkeeping. Mutated only under the owning
    DeviceQuarantine's lock."""

    __slots__ = ("ordinal", "state", "strikes", "last_reason",
                 "probe_backoff_s", "next_probe_t", "episodes", "dumped")

    def __init__(self, ordinal: int, probe_backoff_s: float):
        self.ordinal = ordinal
        self.state = HEALTHY
        self.strikes = 0
        self.last_reason = ""
        self.probe_backoff_s = probe_backoff_s
        self.next_probe_t: float | None = None
        self.episodes = 0          # quarantine entries over the lifetime
        self.dumped = False        # flight dump written for this episode


class DeviceQuarantine:
    """The per-ordinal HEALTHY → SUSPECT → QUARANTINED → PROBATION state
    machine. Pure bookkeeping under one lock plus a fake-able clock, so
    tests drive the full cycle deterministically; the probe *execution*
    lives on the owning policy."""

    def __init__(self, *, strikes: int | None = None,
                 probe_backoff_s: float = 0.5,
                 probe_backoff_max_s: float = 30.0,
                 clock=time.monotonic):
        # env knob first (docs/SERVING.md §Self-healing dispatch), then
        # the constructor default: K strikes evict the ordinal
        self.strikes_limit = max(1, strikes if strikes is not None
                                 else _env_int("CORDA_TPU_QUARANTINE_STRIKES", 3))
        self.probe_backoff_s = max(1e-3, float(probe_backoff_s))
        self.probe_backoff_max_s = max(self.probe_backoff_s,
                                       float(probe_backoff_max_s))
        self._clock = clock
        self._lock = threading.Lock()
        self._ordinals: dict[int, _OrdinalHealth] = {}

    def _slot_locked(self, ordinal: int) -> _OrdinalHealth:
        slot = self._ordinals.get(ordinal)
        if slot is None:
            slot = self._ordinals[ordinal] = _OrdinalHealth(
                ordinal, self.probe_backoff_s
            )
        return slot

    # ------------------------------------------------------------- reads
    def state(self, ordinal: int) -> str:
        with self._lock:
            return self._slot_locked(ordinal).state

    def blocked(self, ordinal: int) -> bool:
        """True while the ordinal must receive no scheduler traffic."""
        with self._lock:
            return self._slot_locked(ordinal).state in (
                QUARANTINED, PROBATION
            )

    def active_count(self) -> int:
        with self._lock:
            return sum(
                1 for s in self._ordinals.values()
                if s.state in (QUARANTINED, PROBATION)
            )

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "strikes_limit": self.strikes_limit,
                "ordinals": {
                    str(o): {
                        "state": s.state,
                        "strikes": s.strikes,
                        "last_reason": s.last_reason,
                        "episodes": s.episodes,
                        "probe_backoff_s": round(s.probe_backoff_s, 6),
                    }
                    for o, s in sorted(self._ordinals.items())
                },
            }

    # ------------------------------------------------------- transitions
    #
    # Counter increments happen OUTSIDE the state lock throughout this
    # module: the registered gauges (serving.quarantine.active /
    # serving.breaker.state) read these state machines from UNDER the
    # metric registry's lock at snapshot time, so taking the registry
    # lock (counter lookup) while holding a state lock would be exactly
    # the A→B/B→A inversion the lockwatch soak exists to catch.

    def strike(self, ordinal: int, reason: str) -> bool:
        """One strike against the ordinal (dispatch failure, fired hedge,
        watchdog eviction). Returns True exactly when this strike ENTERS
        quarantine — the caller owes the once-per-episode flight dump."""
        now = self._clock()
        entered = False
        counted = False
        with self._lock:
            slot = self._slot_locked(ordinal)
            if slot.state not in (QUARANTINED, PROBATION):
                # an already-evicted ordinal takes no further strikes;
                # probes own its readmission
                counted = True
                slot.strikes += 1
                slot.last_reason = reason
                if slot.strikes < self.strikes_limit:
                    slot.state = SUSPECT
                else:
                    slot.state = QUARANTINED
                    slot.episodes += 1
                    slot.dumped = False
                    slot.probe_backoff_s = self.probe_backoff_s
                    slot.next_probe_t = now + slot.probe_backoff_s
                    entered = True
        if counted:
            _metrics().counter("serving.quarantine.strikes").inc()
        if entered:
            _metrics().counter("serving.quarantine.entered").inc()
        return entered

    def healthy_settle(self, ordinal: int) -> None:
        """A clean device settle heals a SUSPECT back to HEALTHY (strikes
        only accumulate across consecutive trouble, not over a lifetime
        of good service). Quarantined/probation ordinals are untouched —
        only a canary verdict readmits them."""
        with self._lock:
            slot = self._slot_locked(ordinal)
            if slot.state == SUSPECT:
                slot.state = HEALTHY
                slot.strikes = 0
                slot.last_reason = ""

    def due_probe(self, now: float | None = None) -> int | None:
        """The next quarantined ordinal whose probe backoff elapsed —
        transitioned to PROBATION here so no second probe can race in
        before the verdict lands."""
        if now is None:
            now = self._clock()
        with self._lock:
            for o in sorted(self._ordinals):
                slot = self._ordinals[o]
                if (slot.state == QUARANTINED
                        and slot.next_probe_t is not None
                        and now >= slot.next_probe_t):
                    slot.state = PROBATION
                    return o
        return None

    def probe_result(self, ordinal: int, ok: bool) -> None:
        """The canary verdict: readmit (HEALTHY, strikes cleared, backoff
        reset) or return to QUARANTINED with the backoff doubled."""
        now = self._clock()
        counted = None
        with self._lock:
            slot = self._slot_locked(ordinal)
            if slot.state != PROBATION:
                return  # stale verdict (reset raced the probe)
            if ok:
                slot.state = HEALTHY
                slot.strikes = 0
                slot.last_reason = ""
                slot.next_probe_t = None
                slot.probe_backoff_s = self.probe_backoff_s
                slot.dumped = False
                counted = "serving.quarantine.readmitted"
            else:
                slot.state = QUARANTINED
                slot.probe_backoff_s = min(
                    slot.probe_backoff_s * 2.0, self.probe_backoff_max_s
                )
                slot.next_probe_t = now + slot.probe_backoff_s
                counted = "serving.quarantine.probe_failures"
        if counted:
            _metrics().counter(counted).inc()

    def claim_episode_dump(self, ordinal: int) -> bool:
        """True exactly once per quarantine episode — the flight-dump
        latch (a second strike or snapshot in the same episode must not
        write a second dump)."""
        with self._lock:
            slot = self._slot_locked(ordinal)
            if slot.state not in (QUARANTINED, PROBATION) or slot.dumped:
                return False
            slot.dumped = True
            return True


class CircuitBreaker:
    """One ordinal's breaker: K consecutive device failures or hedge
    losses trip it OPEN (the scheduler drops the ordinal from the stripe
    set; with every ordinal open the whole tier host-routes), an
    exponential-backoff HALF_OPEN canary closes it. The policy keeps one
    instance per ordinal (``breaker_for``); the mesh rollup is the
    ``serving.breaker.state`` gauge (0 closed / 1 open / 2 half-open)."""

    def __init__(self, *, threshold: int = 3, backoff_s: float = 1.0,
                 backoff_max_s: float = 60.0, clock=time.monotonic):
        self.threshold = max(1, int(threshold))
        self.backoff_s = max(1e-3, float(backoff_s))
        self.backoff_max_s = max(self.backoff_s, float(backoff_max_s))
        self._clock = clock
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._cur_backoff = self.backoff_s

    @property
    def state(self) -> int:
        return self._state

    def allow_device(self) -> bool:
        """False while the device tier is evicted (open or probing) —
        scheduler batches host-route; only canary probes touch the
        device."""
        return self._state == BREAKER_CLOSED

    def record_failure(self) -> bool:
        """One device failure / hedge loss; returns True when this one
        TRIPS the breaker open. (Counters bump outside the state lock —
        the serving.breaker.state gauge reads it from under the registry
        lock.)"""
        tripped = False
        with self._lock:
            self._consecutive += 1
            if (self._state == BREAKER_CLOSED
                    and self._consecutive >= self.threshold):
                self._state = BREAKER_OPEN
                self._opened_at = self._clock()
                self._cur_backoff = self.backoff_s
                tripped = True
        if tripped:
            _metrics().counter("serving.breaker.opened").inc()
        return tripped

    def record_success(self) -> None:
        """A clean device settle breaks the failure streak (only reached
        while CLOSED — open/half-open tiers serve no scheduler
        traffic)."""
        with self._lock:
            self._consecutive = 0

    def probe_due(self, now: float | None = None) -> bool:
        """True when the open breaker's backoff elapsed — transitions to
        HALF_OPEN here, so exactly one canary owns the verdict."""
        if now is None:
            now = self._clock()
        with self._lock:
            if (self._state == BREAKER_OPEN
                    and now >= self._opened_at + self._cur_backoff):
                self._state = BREAKER_HALF_OPEN
                return True
        return False

    def probe_result(self, ok: bool) -> None:
        counted = None
        with self._lock:
            if self._state != BREAKER_HALF_OPEN:
                return
            if ok:
                self._state = BREAKER_CLOSED
                self._consecutive = 0
                self._cur_backoff = self.backoff_s
                counted = "serving.breaker.closed"
            else:
                self._state = BREAKER_OPEN
                self._opened_at = self._clock()
                self._cur_backoff = min(
                    self._cur_backoff * 2.0, self.backoff_max_s
                )
                counted = "serving.breaker.opened"
        if counted:
            _metrics().counter(counted).inc()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "state_name": {
                    BREAKER_CLOSED: "closed", BREAKER_OPEN: "open",
                    BREAKER_HALF_OPEN: "half-open",
                }[self._state],
                "consecutive_failures": self._consecutive,
                "threshold": self.threshold,
                "backoff_s": round(self._cur_backoff, 6),
            }


class ResiliencePolicy:
    """The facade the scheduler consults. One instance per scheduler
    (the process-global scheduler's policy is also the process-global
    ``active_policy()`` the flight recorder snapshots).

    ``probe_runner`` overrides the canary execution for tests — a
    callable ``(ordinal) -> bool``; the default dispatches the
    known-answer batch through ``dispatch_signature_rows`` and demands
    device settlement plus exact verdicts."""

    def __init__(self, *, strikes: int | None = None,
                 hedge_factor: float | None = None,
                 hedge_min_s: float = 0.05, hedge_max_s: float = 30.0,
                 probe_backoff_s: float = 0.5,
                 probe_backoff_max_s: float = 30.0,
                 breaker_threshold: int = 3,
                 breaker_backoff_s: float = 1.0,
                 breaker_backoff_max_s: float = 60.0,
                 redispatch_limit: int = 2,
                 flight_dump_on_quarantine: bool = True,
                 probe_timeout_s: float = 600.0,
                 probe_runner=None, clock=time.monotonic):
        self.hedge_factor = (
            hedge_factor if hedge_factor is not None
            else _env_float("CORDA_TPU_HEDGE_FACTOR", 4.0)
        )
        self.hedge_min_s = float(hedge_min_s)
        self.hedge_max_s = max(self.hedge_min_s, float(hedge_max_s))
        self.redispatch_limit = max(0, int(redispatch_limit))
        self.flight_dump_on_quarantine = bool(flight_dump_on_quarantine)
        # canary readback bound: generous enough for a cold remote
        # compile (~3 min on the tunnel), but FINITE — an unbounded
        # collect on a wedged readback would park the probe thread with
        # its _probing key held and strand the ordinal in PROBATION
        # forever, killing readmission for the rest of the process
        self.probe_timeout_s = max(1e-3, float(probe_timeout_s))
        self.quarantine = DeviceQuarantine(
            strikes=strikes, probe_backoff_s=probe_backoff_s,
            probe_backoff_max_s=probe_backoff_max_s, clock=clock,
        )
        # one breaker per ordinal, created on first contact (PR 13:
        # per-device breaker scope — one sick chip must not evict the
        # other seven from the stripe set)
        self._breaker_kwargs = dict(
            threshold=breaker_threshold, backoff_s=breaker_backoff_s,
            backoff_max_s=breaker_backoff_max_s, clock=clock,
        )
        self._breakers: dict[int, CircuitBreaker] = {}
        self._clock = clock
        self._probe_runner = probe_runner
        self._lock = threading.Lock()
        self._probing: set = set()     # probe keys with a runner in flight
        self._canary = None            # lazily built known-answer rows
        self._shapes = None            # ShapeTable from the attached scheduler
        self._monitor = None           # the devicemon we subscribed to

    # ---------------------------------------------------------- breakers
    def breaker_for(self, ordinal: int) -> CircuitBreaker:
        """The given ordinal's breaker, created on first use."""
        o = int(ordinal)
        with self._lock:
            br = self._breakers.get(o)
            if br is None:
                br = self._breakers[o] = CircuitBreaker(
                    **self._breaker_kwargs
                )
            return br

    @property
    def breaker(self) -> CircuitBreaker:
        """Single-chip compatibility view: the DEFAULT ordinal's breaker
        (PR 9 callers and drills read ``policy.breaker.state``; on one
        chip the default ordinal IS the device tier)."""
        from corda_tpu.observability.devicemon import (
            default_device_ordinal,
        )

        return self.breaker_for(default_device_ordinal())

    def breaker_state_mesh(self) -> int:
        """Whole-mesh breaker rollup: OPEN only when EVERY known
        ordinal's breaker is open (the stripe set is empty — the tier is
        down), HALF_OPEN while any ordinal is probing, else CLOSED.
        Reads existing breakers only (no creation side effect — the
        gauge calls this from under the registry lock)."""
        with self._lock:
            breakers = list(self._breakers.values())
        if not breakers:
            return BREAKER_CLOSED
        states = [br.state for br in breakers]
        if all(s == BREAKER_OPEN for s in states):
            return BREAKER_OPEN
        if any(s == BREAKER_HALF_OPEN for s in states):
            return BREAKER_HALF_OPEN
        return BREAKER_CLOSED

    # --------------------------------------------------------- lifecycle
    def attach(self, scheduler) -> None:
        """Bind to the scheduler that consults this policy: pick up its
        shape table (canary pad bucket), subscribe to devicemon health
        events (watchdog evictions become strikes), and become the
        process-visible policy for snapshots/flight dumps."""
        self._shapes = getattr(scheduler, "_shapes", None)
        try:
            from corda_tpu.observability.devicemon import devicemon

            mon = devicemon()
            mon.subscribe(self.on_device_event)
            self._monitor = mon
        except Exception:
            self._monitor = None
        register_policy(self)

    def detach(self, scheduler) -> None:
        mon = self._monitor
        if mon is not None:
            mon.unsubscribe(self.on_device_event)
            self._monitor = None
        unregister_policy(self)

    # ----------------------------------------------------------- routing
    def admit_device(self, ordinal: int) -> bool:
        """The per-dispatch gate: False routes the whole batch to host.
        The ordinal's breaker first, then its quarantine."""
        if not self.breaker_for(ordinal).allow_device():
            _metrics().counter("serving.breaker.host_routed").inc()
            return False
        if self.quarantine.blocked(ordinal):
            _metrics().counter("serving.quarantine.host_routed").inc()
            return False
        return True

    def admit_ordinal(self, ordinal: int) -> bool:
        """Counter-free eligibility read for stripe-set membership:
        True while the ordinal's breaker is closed and it is not
        quarantined. ``admit_device`` remains the per-dispatch gate
        that counts host-routes; this one is consulted once per
        placement decision for EVERY ordinal, so it must not inflate
        those counters."""
        return (self.breaker_for(ordinal).allow_device()
                and not self.quarantine.blocked(ordinal))

    def hedge_deadline_s(self, ordinal: int,
                         fallback_ewma_s: float) -> float | None:
        """The in-flight deadline for one dispatched batch: execute-wall
        EWMA × hedge factor, clamped to [hedge_min_s, hedge_max_s].
        Devicemon's per-ordinal EWMA when it is on and has samples, else
        the scheduler's own latency EWMA. None (no hedging) before any
        settle has seeded an EWMA — a cold first dispatch may legally be
        a multi-minute compile, and hedging it would fight the compile
        cache."""
        ewma = 0.0
        try:
            from corda_tpu.observability.devicemon import active_devicemon

            mon = active_devicemon()
            if mon is not None:
                ewma = mon.execute_ewma(ordinal)
        except Exception:
            ewma = 0.0
        if ewma <= 0.0:
            ewma = max(float(fallback_ewma_s), 0.0)
        if ewma <= 0.0:
            return None
        return min(max(ewma * self.hedge_factor, self.hedge_min_s),
                   self.hedge_max_s)

    # ------------------------------------------------------ feed points
    def on_dispatch_failure(self, ordinal: int) -> None:
        """A device dispatch raised (real or injected): one strike, one
        breaker failure — both against the ordinal the batch was placed
        on."""
        self._strike(ordinal, "dispatch-failure")
        self.breaker_for(ordinal).record_failure()

    def on_hedge_fired(self, ordinal: int) -> None:
        """A batch blew its in-flight deadline: stall evidence — a
        strike, but not yet a breaker failure (the device may still win
        the race; the loss is counted when the host does)."""
        self._strike(ordinal, "hedge-stall")

    def on_hedge_won_host(self, ordinal: int) -> None:
        """The hedge completed on host before the device: a loss toward
        the stalled ordinal's breaker."""
        self.breaker_for(ordinal).record_failure()

    def on_hedge_won_sibling(self, ordinal: int) -> None:
        """A SIBLING chip finished the hedged batch before the original
        device: same per-ordinal evidence as a host win — the loss lands
        on the ORIGINAL ordinal's breaker, while the sibling's own
        clean settle speaks for itself."""
        self.breaker_for(ordinal).record_failure()

    def on_settle_ok(self, ordinal: int) -> None:
        self.quarantine.healthy_settle(ordinal)
        self.breaker_for(ordinal).record_success()

    def on_device_event(self, event: dict) -> None:
        """The devicemon subscription hook: a watchdog ``device.unhealthy``
        eviction is a strike against the flagged ordinal."""
        if event.get("kind") != "device.unhealthy":
            return
        ordinal = event.get("device")
        if isinstance(ordinal, int):
            self._strike(ordinal, f"watchdog:{event.get('reason', '')}")

    def _strike(self, ordinal: int, reason: str) -> None:
        entered = self.quarantine.strike(ordinal, reason)
        if entered and self.flight_dump_on_quarantine:
            self._quarantine_dump(ordinal)

    def _quarantine_dump(self, ordinal: int) -> None:
        """One flight-recorder dump per quarantine episode — the black
        box for the eviction, readable via ``read_flight_dump``. The
        latch lives on the episode, so watchdog re-flags cannot spam
        dumps; a failing dump must never break the strike path."""
        if not self.quarantine.claim_episode_dump(ordinal):
            return
        try:
            from corda_tpu.observability.slo import flight_dump

            flight_dump(reason=f"device-quarantine:{ordinal}")
        except Exception:
            pass

    # ------------------------------------------------------------ probes
    def maybe_probe(self, now: float | None = None, *,
                    sync: bool = False) -> None:
        """Launch any due canary probe (quarantine readmission and/or
        breaker half-open). Called from the scheduler's hedge thread on
        every wake-up; ``sync=True`` runs the probe inline (tests, and
        fake-clock drives)."""
        if now is None:
            now = self._clock()
        ordinal = self.quarantine.due_probe(now)
        if ordinal is not None:
            self._launch_probe(("quarantine", ordinal), sync)
        with self._lock:
            breakers = list(self._breakers.items())
        for o, br in breakers:
            if br.probe_due(now):
                self._launch_probe(("breaker", o), sync)

    def _launch_probe(self, key: tuple, sync: bool) -> None:
        with self._lock:
            if key in self._probing:
                return
            self._probing.add(key)
        kind, ordinal = key
        if kind == "quarantine":
            _metrics().counter("serving.quarantine.probes").inc()
        else:
            _metrics().counter("serving.breaker.probes").inc()
        if sync:
            self._probe(key)
        else:
            threading.Thread(
                target=self._probe, args=(key,),
                name="serving-canary", daemon=True,
            ).start()

    def _probe(self, key: tuple) -> None:
        kind, ordinal = key
        try:
            ok = self._run_canary(0 if ordinal is None else ordinal)
        except Exception:
            ok = False
        finally:
            with self._lock:
                self._probing.discard(key)
        if kind == "quarantine":
            self.quarantine.probe_result(ordinal, ok)
        else:
            self.breaker_for(
                0 if ordinal is None else ordinal
            ).probe_result(ok)

    def _canary_rows(self):
        """The known-answer batch: valid signatures plus one tampered —
        a device echoing garbage all-True verdicts must fail the probe,
        not pass it."""
        if self._canary is None:
            from corda_tpu.crypto import generate_keypair, sign

            kp = generate_keypair()
            rows, expected = [], []
            for i in range(3):
                msg = b"resilience-canary-%d" % i
                rows.append((kp.public, sign(kp.private, msg), msg))
                expected.append(True)
            key, sig, msg = rows[-1]
            rows[-1] = (key, b"\x00" * len(sig), msg)
            expected[-1] = False
            self._canary = (rows, expected)
        return self._canary

    def _run_canary(self, ordinal: int) -> bool:
        runner = self._probe_runner
        if runner is not None:
            return bool(runner(ordinal))
        from corda_tpu.verifier.batch import dispatch_signature_rows

        rows, expected = self._canary_rows()
        bucket = (
            self._shapes.bucket_for(len(rows))
            if self._shapes is not None else None
        )
        # the canary must exercise the SPECIFIC ordinal it readmits —
        # an unpinned probe would land on the backend default and could
        # readmit a still-sick chip on a healthy sibling's evidence
        try:
            from corda_tpu.parallel.mesh import device_for_ordinal

            device = device_for_ordinal(ordinal)
        except Exception:
            device = None
        pending = dispatch_signature_rows(
            rows, use_device=True, min_bucket=bucket, device=device,
        )
        # bounded wait on the readback: a probe against a wedged device
        # must FAIL (backoff doubles, a later probe retries) rather than
        # block forever — collect() itself has no timeout
        deadline = time.monotonic() + self.probe_timeout_s
        while not pending.ready():
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)
        mask = pending.collect()
        if pending.device_rows != len(rows):
            # some (or all) rows silently failed over to host: the host
            # verdicts are right, but they prove nothing about the device
            return False
        return [bool(v) for v in mask] == expected

    # ----------------------------------------------------------- surface
    def snapshot(self) -> dict:
        return {
            "enabled": True,
            "hedge": {
                "factor": self.hedge_factor,
                "min_s": self.hedge_min_s,
                "max_s": self.hedge_max_s,
            },
            "quarantine": self.quarantine.snapshot(),
            "breaker": self._breaker_snapshot(),
        }

    def _breaker_snapshot(self) -> dict:
        """Mesh rollup plus per-ordinal detail, shape-compatible with
        the PR 9 single-breaker snapshot (``state``/``state_name``/
        ``threshold`` at the top level) so flight-dump consumers keep
        parsing."""
        with self._lock:
            items = sorted(self._breakers.items())
        state = self.breaker_state_mesh()
        return {
            "state": state,
            "state_name": {
                BREAKER_CLOSED: "closed", BREAKER_OPEN: "open",
                BREAKER_HALF_OPEN: "half-open",
            }[state],
            "threshold": max(1, int(self._breaker_kwargs["threshold"])),
            "per_ordinal": {
                str(o): br.snapshot() for o, br in items
            },
        }


# ------------------------------------------------- process-global surface
#
# The policy attached to the live scheduler is the one snapshots and the
# flight recorder report; gauges read THROUGH this slot (the devicemon /
# serving-gauge pattern) so a shut-down scheduler's policy is never
# pinned by the metric registry.

_active_policy: ResiliencePolicy | None = None
_policy_lock = threading.Lock()


def register_policy(policy: ResiliencePolicy) -> None:
    global _active_policy
    with _policy_lock:
        _active_policy = policy


def unregister_policy(policy: ResiliencePolicy) -> None:
    global _active_policy
    with _policy_lock:
        if _active_policy is policy:
            _active_policy = None


def active_policy() -> ResiliencePolicy | None:
    return _active_policy


def resilience_section() -> dict:
    """The ``resilience`` section of ``monitoring_snapshot()`` and the
    flight recorder: the live policy's state machine view, or a bare
    disabled marker."""
    policy = _active_policy
    if policy is None:
        return {"enabled": False}
    try:
        return policy.snapshot()
    except Exception:
        return {"enabled": False}


def _register_gauges() -> None:
    m = _metrics()

    def breaker_state():
        # mesh rollup, and deliberately NOT the `breaker` property: a
        # gauge read must not create breaker slots as a side effect
        p = _active_policy
        try:
            return p.breaker_state_mesh() if p is not None else 0
        except Exception:
            return 0

    def quarantine_active():
        p = _active_policy
        try:
            return p.quarantine.active_count() if p is not None else 0
        except Exception:
            return 0

    m.gauge("serving.breaker.state", breaker_state)
    m.gauge("serving.quarantine.active", quarantine_active)


_register_gauges()
