"""Compiled-shape buckets for the device scheduler.

Every device kernel in ``corda_tpu/ops`` compiles once per pad bucket
(``_blockpack.pow2_at_least`` — power-of-two row counts, floored at the
pallas block width). The scheduler must never hand XLA a shape it has not
seen before mid-traffic: a ragged batch size on a tunneled backend costs a
multi-minute remote compile in the middle of request latency (the r4
trader capture lost a whole section to exactly one fresh Mosaic shape).

So the shape set is DATA, not code: ``tools_block_sweep.py`` measures the
kernels on the real chip and emits its chosen block widths + bucket ladder
to the checked-in ``shapes.json`` next to this module; the scheduler loads
it at startup. When the file is missing or unreadable the built-in default
below applies — the same pow-of-two ladder the kernels would derive on
their own, so behavior degrades to the status quo, never to a crash.

Override precedence: ``CORDA_TPU_SERVING_SHAPES`` (path to a JSON file)
> checked-in ``shapes.json`` > ``DEFAULT_SHAPES``.
"""

from __future__ import annotations

import json
import os
import threading

# Safe built-in default: the bucket ladder implied by the production
# pallas block width (128) up to the bench batch shape (8192). Matches
# what bucket_floor()/pow2_at_least() would produce today, so loading
# nothing changes nothing.
DEFAULT_SHAPES: dict = {
    "source": "built-in default",
    "ed25519_block": 128,
    "ecdsa_block": 128,
    "buckets": [128, 256, 512, 1024, 2048, 4096, 8192],
}

_SHAPES_PATH = os.path.join(os.path.dirname(__file__), "shapes.json")


class ShapeTable:
    """The scheduler's pad-bucket chooser: ``bucket_for(n)`` returns the
    smallest configured bucket ≥ n (None when n exceeds the ladder — the
    kernels then fall back to their own pow2 padding)."""

    def __init__(self, data: dict):
        buckets = data.get("buckets") or DEFAULT_SHAPES["buckets"]
        self.buckets: list[int] = sorted(
            int(b) for b in buckets if int(b) > 0
        ) or list(DEFAULT_SHAPES["buckets"])
        self.source: str = str(data.get("source", "unknown"))
        self.data = dict(data)

    def bucket_for(self, n_rows: int, floor: int | None = None) -> int | None:
        """Smallest bucket covering ``n_rows`` (and ``floor``, a caller
        hint such as the notary's pinned window size)."""
        want = max(n_rows, floor or 0)
        for b in self.buckets:
            if b >= want:
                return b
        return None

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            data = json.load(f)
        if isinstance(data, dict) and data.get("buckets"):
            return data
    except Exception:
        pass
    return None


def load_shape_table() -> ShapeTable:
    """Resolve the shape table by the documented precedence. Never raises:
    a corrupt or missing file yields the built-in default."""
    override = os.environ.get("CORDA_TPU_SERVING_SHAPES", "").strip()
    for path in ([override] if override else []) + [_SHAPES_PATH]:
        data = _read_json(path)
        if data is not None:
            data.setdefault("source", path)
            return ShapeTable(data)
    return ShapeTable(dict(DEFAULT_SHAPES))


_cached: ShapeTable | None = None
_cache_lock = threading.Lock()


def shape_table() -> ShapeTable:
    """Process-cached table (one file read per process)."""
    global _cached
    if _cached is None:
        with _cache_lock:
            if _cached is None:
                _cached = load_shape_table()
    return _cached
