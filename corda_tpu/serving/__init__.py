"""Serving layer — the continuous-batching device scheduler shared by the
verifier, notary, and flow hot paths (docs/SERVING.md).

One process-global dispatch queue in front of the signature kernels:
requests from concurrent clients coalesce into shape-bucketed device
batches with priority classes, deadlines, backpressure, and adaptive
batch sizing — the request-coalescing layer the committee-consensus EdDSA
and FPGA ECDSA verification-engine papers (PAPERS.md) credit for their
throughput, and the role the reference delegates to the Artemis verifier
queue in front of OutOfProcessTransactionVerifierService.
"""

from .resilience import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    HEALTHY,
    PROBATION,
    QUARANTINED,
    SUSPECT,
    CircuitBreaker,
    DeviceQuarantine,
    ResiliencePolicy,
    active_policy,
    resilience_section,
)
from .scheduler import (
    BULK,
    INTERACTIVE,
    SERVICE,
    DeadlineExceededError,
    DeviceScheduler,
    FuturePending,
    RowResult,
    SchedulerClosedError,
    SchedulerSaturatedError,
    ServingError,
    configure_scheduler,
    device_scheduler,
    shutdown_scheduler,
)
from .shapes import DEFAULT_SHAPES, ShapeTable, load_shape_table, shape_table

__all__ = [
    "BREAKER_CLOSED",
    "BREAKER_HALF_OPEN",
    "BREAKER_OPEN",
    "BULK",
    "CircuitBreaker",
    "DeviceQuarantine",
    "HEALTHY",
    "INTERACTIVE",
    "PROBATION",
    "QUARANTINED",
    "ResiliencePolicy",
    "SUSPECT",
    "SERVICE",
    "active_policy",
    "resilience_section",
    "DeadlineExceededError",
    "DeviceScheduler",
    "FuturePending",
    "RowResult",
    "SchedulerClosedError",
    "SchedulerSaturatedError",
    "ServingError",
    "configure_scheduler",
    "device_scheduler",
    "shutdown_scheduler",
    "DEFAULT_SHAPES",
    "ShapeTable",
    "load_shape_table",
    "shape_table",
]
