"""The process-global continuous-batching device scheduler.

BENCH_r05 showed the batch kernels fast (ed25519 13.5× host) while the
end-to-end latency paths lost to host because every caller owned its own
ad-hoc batching: the verifier's fixed-window flusher, the notary's submit
path, and flows dispatching singleton verifies — three queues in front of
one device, none aware of the others. This module is the single
device-dispatch subsystem in front of the kernels, the same scheduler
shape an inference-serving stack uses (continuous batching / request
coalescing; the reference's closest analogue is the Artemis verifier
queue feeding OutOfProcessTransactionVerifierService — one queue, many
producers).

Core loop (``_dispatch_loop``):

- requests enqueue with a PRIORITY CLASS (``INTERACTIVE`` flow verifies,
  ``SERVICE`` verifier traffic, ``BULK`` notary windows) and an optional
  deadline;
- the scheduler launches a batch whenever the device pipeline has a free
  slot and work is pending — there is NO fixed batching window, so a
  single request on an idle scheduler dispatches immediately instead of
  paying ``window_s``, and coalescing emerges from concurrency: whatever
  arrived while the previous batch was in flight forms the next batch;
- rows pad to a small set of compiled batch shapes (``shapes.py``, seeded
  from the block-sweep capture) so ragged sizes never trigger fresh XLA
  compiles mid-traffic;
- admission control is a bounded queue (reject with
  ``SchedulerSaturatedError``) and over-deadline work is SHED at batch
  assembly (``DeadlineExceededError``), with per-class reserved shares so
  a notary load spike cannot starve interactive flows (and vice versa);
- batch size adapts to observed arrival rate × device latency (EWMA),
  splitting a deep queue into pipeline-depth chunks instead of one giant
  serial batch;
- up to ``depth`` batches ride the device concurrently (dispatch is the
  async half of ``dispatch_signature_rows``; a separate collector thread
  harvests readbacks in COMPLETION order — ``serving.settle_reorder``
  counts out-of-order settles), preserving the round-trip overlap the
  notary and wavefront pipelines rely on.

Mesh scheduling: with more than one visible accelerator (or
``CORDA_TPU_MESH=1``) the scheduler stripes batches across a **stripe
set** of eligible ordinals — every ``jax.devices()`` ordinal minus
watchdog-evicted (devicemon ``unhealthy_ordinals``), quarantined and
breaker-open ones — placing each batch by power-of-two-choices over
(per-ordinal in-flight depth, execute-wall EWMA). When fill is high, a
full homogeneous ed25519 bucket fuses into ONE ``shard_map`` mega-batch
over the whole mesh, with the consumed-set delta all-gathered over ICI
(``parallel/mesh.py``'s ``distributed_verify_step`` — the notary-commit
collective). The PR 9 resilience machinery runs per-ordinal here:
hedges re-route to a *sibling chip* before conceding to the host leg,
canary probes pin the specific ordinal they readmit, and the breaker
opens mesh-wide only when every ordinal is down. See docs/SERVING.md
§Mesh scheduling.

Degradation contract: the ``serving.dispatch`` faultinject site sits in
front of every per-ordinal device dispatch (``serving.mesh_dispatch``
in front of every fused mega-batch); an injected (or real) dispatch
failure fails over the whole batch to the host reference path —
identical verdicts, ``serving.device_failover`` counted — and the
per-bucket ``verifier.device`` site below still covers partial
failures. Metrics live in the process registry (``node_metrics()``)
under ``serving.*``.
"""

from __future__ import annotations

import math
import os
import queue as _queue
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutTimeout

import numpy as np

from corda_tpu.observability import (
    NOOP_SPAN,
    SPAN_SERVING_BATCH,
    SPAN_SERVING_QUEUE,
    tracer,
)
from corda_tpu.observability.devicemon import (
    active_devicemon,
    default_device_ordinal,
)
from corda_tpu.observability.profiler import (
    KERNEL_SERVING_DISPATCH,
    active_profiler,
    stamp_span,
)
from corda_tpu.flows.overload import remaining_deadline
from corda_tpu.observability.contention import register_wait_site
from corda_tpu.observability.flowprof import active_flowprof
from corda_tpu.observability.slo import active_slo

from .shapes import shape_table

# the sampler's blocked/running classifier (concurrency observatory):
# dispatcher/hedge threads sampled inside these loops are parked on the
# scheduler monitor awaiting work, not burning CPU
register_wait_site("scheduler.py", "_dispatch_loop", "lock_wait")
register_wait_site("scheduler.py", "_hedge_loop", "lock_wait")

# ------------------------------------------------------------ priorities

INTERACTIVE = "interactive"  # flow hot path: singleton / few-row verifies
SERVICE = "service"          # verifier service traffic
BULK = "bulk"                # notary windows / bulk resolve sweeps

_CLASSES = (INTERACTIVE, SERVICE, BULK)

# Reserved share of one batch per class. Classes are drained in this
# order up to their share; leftover capacity then fills OLDEST-FIRST
# across all classes, so neither a bulk spike (starving interactive) nor
# an interactive flood (starving bulk) can monopolize the device.
_RESERVED = {INTERACTIVE: 0.25, SERVICE: 0.25, BULK: 0.5}


class ServingError(Exception):
    """Base for scheduler-side request failures."""


class SchedulerClosedError(ServingError):
    pass


class SchedulerSaturatedError(ServingError):
    """Admission control: the bounded queue is full. Callers either
    surface the rejection or degrade to their direct dispatch path."""


class DeadlineExceededError(ServingError):
    """The request aged past its deadline before a device slot opened;
    it was shed instead of wasting a batch on an answer nobody waits for."""


class RowResult:
    """What a row-level submission resolves to: the (N,) bool verdict
    mask, how many rows actually settled on device, the sequence number
    of the device batch that served it (shared by every request
    coalesced into that batch — the cross-client coalescing witness),
    and the device ordinal the batch ran on (None for host-settled
    batches) — per-chip attribution even before the mesh scheduler."""

    __slots__ = ("mask", "n_device", "batch_seq", "device")

    def __init__(self, mask: np.ndarray, n_device: int, batch_seq: int,
                 device: int | None = None):
        self.mask = mask
        self.n_device = n_device
        self.batch_seq = batch_seq
        self.device = device


class _Request:
    __slots__ = ("rows", "future", "priority", "use_device", "min_bucket",
                 "enqueued_at", "deadline", "queue_span", "redispatches",
                 "acct")

    def __init__(self, rows, future, priority, use_device, min_bucket,
                 enqueued_at, deadline, queue_span=NOOP_SPAN, acct=None):
        self.rows = rows
        self.future = future
        self.priority = priority
        self.use_device = use_device
        self.min_bucket = min_bucket
        self.enqueued_at = enqueued_at
        self.deadline = deadline
        # open serving.queue span (NOOP for unsampled callers): starts at
        # admission on the submitting thread, finishes on the dispatcher
        # thread when the request leaves the queue for a batch
        self.queue_span = queue_span
        # times this request re-entered the queue after a failed device
        # dispatch (the resilience re-dispatch path) — bounded by the
        # policy's redispatch_limit, then it host-fails-over like before
        self.redispatches = 0
        # flowprof account of the submitting flow (None for untracked
        # callers): dispatch/settle attribute queue_wait/device_execute/
        # host_verify to the flow that asked, across threads
        self.acct = acct


class _InFlight:
    """One dispatched DEVICE batch: the async pending (no readback yet)
    plus the bookkeeping to slice verdicts back per request at collect
    time. Host-routed requests never enter the in-flight pipeline — they
    settle on the scheduler's host pool straight from dispatch.

    With a resilience policy attached the entry also carries the hedge
    state: an in-flight ``deadline``, whether the hedge ``fired``
    (``hedged``), which side completed the futures first (``winner`` —
    claimed exactly once under the scheduler lock; futures are completed
    first-wins either way), and whether the device depth slot was already
    released (``slot_freed`` — decremented exactly once whichever of the
    hedge and the collector gets there first)."""

    __slots__ = ("requests", "pending", "n_rows", "dev_map", "seq", "t0",
                 "span", "device", "deadline", "hedged", "winner",
                 "slot_freed", "compile_keys", "mesh_ordinals")

    def __init__(self, requests, pending, n_rows, dev_map, seq, t0,
                 span=NOOP_SPAN, device=None, compile_keys=frozenset(),
                 mesh_ordinals=()):
        self.requests = requests
        self.pending = pending
        self.n_rows = n_rows
        self.dev_map = dev_map      # (request index, row offset) per dev row
        self.seq = seq
        self.t0 = t0
        self.span = span            # serving.batch span, finished at settle
        self.device = device        # ordinal the dispatch ran on
        self.compile_keys = compile_keys  # shape keys this dispatch touched
        # a fused shard_map mega-batch runs on EVERY one of these ordinals
        # (device stays None — no single chip owns it); settle attribution
        # fans back out over them via record_sharded_settle
        self.mesh_ordinals = tuple(mesh_ordinals)
        self.deadline = None        # monotonic hedge deadline (None: unhedged)
        self.hedged = False         # the hedge timer fired for this batch
        self.winner = None          # None | "device" | "sibling" | "host"
        self.slot_freed = False     # depth slot released exactly once


def _metrics():
    from corda_tpu.node.monitoring import node_metrics

    return node_metrics()


def _pending_ready(pending) -> bool:
    """Non-blocking probe: has this in-flight batch's device work
    finished? Unknown pending types read as not-ready so the collector
    falls back to the FIFO blocking path for them."""
    probe = getattr(pending, "ready", None)
    if probe is None:
        return False
    try:
        return bool(probe())
    except Exception:
        return False


def _complete(future: Future, result=None, error: Exception | None = None):
    """Complete tolerating caller-side cancellation."""
    try:
        if error is None:
            future.set_result(result)
        else:
            future.set_exception(error)
    except Exception:
        pass


def _consumed_rows(msgs: list[bytes]) -> np.ndarray:
    """Per-row consumed-state digests for the mega-batch collective: the
    (N, 8)-int32 view of each signed payload's SHA-256 — the row shape
    ``distributed_verify_step``'s ``spent_hashes`` input shards and
    all-gathers over ICI, so every chip (and the host readback) holds
    the batch's full spent-set delta for a notary commit."""
    import hashlib

    out = np.zeros((len(msgs), 8), dtype=np.int32)
    for i, msg in enumerate(msgs):
        out[i] = np.frombuffer(hashlib.sha256(msg).digest(), dtype="<i4")
    return out


class _MeshPending:
    """``PendingRows``-shaped adapter for one fused shard_map mega-batch:
    the whole batch is ONE device value (the bucket-padded verdict mask)
    plus the all-gathered consumed-set delta and the psum'd accept
    count. Every row is a device row, in dispatch order. A readback
    failure degrades to the host reference path at ``collect()`` —
    the same never-lose-a-future contract as a PendingRows bucket
    fallback."""

    __slots__ = ("_rows", "_mask", "spent_all", "total_valid", "_n",
                 "device_rows", "device_mask", "padded_lanes",
                 "stall_until", "statestore_hits")

    def __init__(self, rows: list, mask, spent_all, total, bucket: int):
        self._rows = rows            # (PublicKey, sig, msg): host fallback
        self._mask = mask
        self.spent_all = spent_all   # (bucket, 8) int32, gathered over ICI
        self.total_valid = total     # psum'd scalar accept count
        self._n = len(rows)
        self.device_rows = len(rows)
        self.device_mask = np.ones(len(rows), dtype=bool)
        self.padded_lanes = int(bucket)
        self.stall_until = None      # injected-stall horizon (faultinject)
        # device scalar from the statestore's fused membership screen
        # over spent_all (docs/STATE_STORE.md); harvested at collect()
        self.statestore_hits = None

    def inject_stall(self, delay_s: float) -> None:
        if delay_s <= 0:
            return
        horizon = time.monotonic() + delay_s
        self.stall_until = max(self.stall_until or 0.0, horizon)

    def ready(self) -> bool:
        from corda_tpu.ops._blockpack import result_ready

        if self.stall_until is not None and \
                time.monotonic() < self.stall_until:
            return False
        return result_ready(self._mask)

    def collect(self) -> np.ndarray:
        if self.stall_until is not None:
            delay = self.stall_until - time.monotonic()
            if delay > 0:
                time.sleep(delay)
        try:
            out = np.asarray(self._mask)[: self._n]
        except Exception:
            from corda_tpu.crypto import is_valid

            _metrics().counter("serving.mesh.megabatch_failover").inc()
            self.device_rows = 0
            self.device_mask[:] = False
            return np.array(
                [is_valid(k, s, m) for k, s, m in self._rows], dtype=bool
            )
        if self.statestore_hits is not None:
            try:
                hits = int(self.statestore_hits)
            except Exception:
                _metrics().counter("statestore.mega_screen_failed").inc()
            else:
                m = _metrics()
                m.counter("statestore.mega_probe_rows").inc(self._n)
                m.counter("statestore.mega_probe_hits").inc(hits)
        return out


class DeviceScheduler:
    """One continuous-batching loop over the signature-verification
    kernels. Construct directly for tests; production code shares the
    process-global instance via ``device_scheduler()``."""

    def __init__(
        self,
        *,
        use_device_default: bool = True,
        max_batch_rows: int | None = None,
        min_batch_rows: int = 256,
        max_queue_rows: int = 131072,
        depth: int = 3,
        host_workers: int = 4,
        shapes=None,
        resilience=None,
        mesh: bool | None = None,
        megabatch_fill: float | None = None,
    ):
        # `shapes`: an explicit ShapeTable override (tests and the smoke
        # harness pin small pad buckets to reuse already-compiled shapes)
        # `resilience`: a ResiliencePolicy the scheduler consults on every
        # dispatch and settle (quarantine routing, hedge deadlines,
        # circuit breaker, re-dispatch — docs/SERVING.md §Self-healing
        # dispatch). None consults CORDA_TPU_RESILIENCE=1 for a default
        # policy; False pins it off.
        # `mesh`: stripe batches across all visible devices (docs/
        # SERVING.md §Mesh scheduling). None consults CORDA_TPU_MESH,
        # else defaults on exactly when >1 real accelerator is attached
        # (the service-mesh activation rule); the probe is deferred to
        # the first device dispatch so construction never touches jax.
        # `megabatch_fill`: bucket-fill fraction at/above which a full
        # homogeneous ed25519 batch fuses into one shard_map mega-batch
        # (CORDA_TPU_MESH_MEGABATCH_FILL, default 0.85).
        self._shapes = shapes or shape_table()
        if resilience is None and os.environ.get(
            "CORDA_TPU_RESILIENCE", ""
        ).strip().lower() in ("1", "true", "on", "yes"):
            from .resilience import ResiliencePolicy

            resilience = ResiliencePolicy()
        self._resilience = resilience or None
        self._use_device_default = use_device_default
        self._max_batch_rows = max_batch_rows or self._shapes.max_bucket
        self._min_batch_rows = min_batch_rows
        self._max_queue_rows = max_queue_rows
        self._lock = threading.Condition()
        self._queues: dict[str, deque] = {c: deque() for c in _CLASSES}
        self._queued_rows = 0
        self._closed = False
        self._paused = False            # test hook: hold assembly
        self._seq = 0
        # dispatcher→collector handoff; the depth bound lives on the
        # _inflight counter (waited on BEFORE device enqueue), not on the
        # queue, so the collector may hold several batches and settle
        # them in COMPLETION order without widening the device pipeline
        self._depth = max(1, depth)
        self._inflight_q: _queue.Queue = _queue.Queue()
        self._inflight = 0
        # host-routed rows settle here, off the device collector thread —
        # a bulk host window must not delay an unrelated device batch's
        # (or another host request's) completion
        self._host_pool = ThreadPoolExecutor(
            max_workers=host_workers, thread_name_prefix="serving-host"
        )
        # cumulative real-vs-padded device lanes: the fill-ratio gauge
        # (dispatcher-thread-only writes; read racily by the gauge)
        self._real_rows = 0
        self._padded_rows = 0
        # ---- mesh striping state (docs/SERVING.md §Mesh scheduling) ----
        self._mesh = mesh               # None until lazily resolved
        if megabatch_fill is None:
            try:
                megabatch_fill = float(os.environ.get(
                    "CORDA_TPU_MESH_MEGABATCH_FILL", "0.85"
                ))
            except ValueError:
                megabatch_fill = 0.85
        self._megabatch_fill = max(0.0, megabatch_fill)
        self._devices = None            # ordinal → jax.Device (lazy)
        # per-ordinal placement state, all under self._lock: reserved
        # in-flight depth (released at settle), the execute-wall EWMA the
        # placement score reads, and per-ordinal dispatch counts (test/
        # bench attribution, reconciled against devicemon)
        self._ord_inflight: dict[int, int] = {}
        self._ord_ewma: dict[int, float] = {}
        self._ord_dispatches: dict[int, int] = {}
        self._place_seq = 0             # rotating first placement choice
        self._stripe_width = 0          # last stripe size (gauge)
        self._mesh_spread_max = 0       # max observed depth spread (gauge)
        # EWMA state: arrival rate (rows/s, ~5 s horizon) and per-batch
        # device latency — their product is the expected arrivals during
        # one round trip, i.e. the natural adaptive batch size
        self._arrival_rate = 0.0
        self._arrival_last = time.monotonic()
        self._latency_ewma = 0.0
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="serving-dispatch", daemon=True
        )
        self._collector = threading.Thread(
            target=self._collect_loop, name="serving-collect", daemon=True
        )
        # hedge monitor (resilience only): armed in-flight entries whose
        # deadline may expire before the collector hears back — shares
        # self._lock (condition) with the dispatcher/collector
        self._hedge_entries: list[_InFlight] = []
        # late-readback reaper threads (one per hedged batch): joined —
        # with a BOUND — at shutdown so a drain still observes the
        # discard counters, but a truly wedged readback cannot hang
        # shutdown or park a host-pool worker forever
        self._reapers: list[threading.Thread] = []
        # (scheme, bucket) shapes that have settled on device at least
        # once: a first-touch dispatch of a NEW shape may legally be a
        # multi-second XLA compile (one compile per scheme × bucket), so
        # only batches whose every shape is warm get a hedge deadline —
        # without this, ramp-up across pad buckets reads as a stall and
        # strikes/trips against a perfectly healthy device
        self._warm_keys: set = set()
        self._hedge: threading.Thread | None = None
        if self._resilience is not None:
            self._resilience.attach(self)
            self._hedge = threading.Thread(
                target=self._hedge_loop, name="serving-hedge", daemon=True
            )
        self._dispatcher.start()
        self._collector.start()
        if self._hedge is not None:
            self._hedge.start()

    # ------------------------------------------------------------- submit
    @property
    def closed(self) -> bool:
        return self._closed

    def submit_rows(
        self,
        rows: list[tuple],
        *,
        priority: str = SERVICE,
        deadline_s: float | None = None,
        use_device: bool | None = None,
        min_bucket: int | None = None,
        trace=None,
    ) -> Future:
        """Enqueue (PublicKey, signature, message) rows; the Future
        resolves to a ``RowResult``. Raises ``SchedulerClosedError`` /
        ``SchedulerSaturatedError`` synchronously (admission control
        rejects at the door, it never queues doomed work).

        ``trace`` is an explicit parent ``TraceContext``/``Span`` for
        callers submitting from a thread that is not the traced request's
        (the notary flusher); same-thread callers inherit the activated
        context automatically. Sampled requests get a ``serving.queue``
        span covering admission→dispatch."""
        if priority not in _CLASSES:
            raise ValueError(f"unknown priority class {priority!r}")
        if deadline_s is None:
            # end-to-end deadline propagation (docs/OVERLOAD.md): a flow
            # carrying a propagated deadline bounds its serving submits
            # automatically — the queue sheds this request the moment the
            # caller's caller has given up. An explicit deadline_s wins.
            rem = remaining_deadline()
            if rem is not None:
                deadline_s = max(0.0, rem)
        rows = list(rows)
        fut: Future = Future()
        if not rows:
            fut.set_result(RowResult(np.zeros(0, dtype=bool), 0, -1))
            return fut
        trc = tracer()
        queue_span = trc.start(
            SPAN_SERVING_QUEUE,
            trace if trace is not None else trc.current(),
            attrs={"priority": priority, "rows": len(rows)},
        )
        now = time.monotonic()
        fp = active_flowprof()
        req = _Request(
            rows, fut, priority,
            self._use_device_default if use_device is None else use_device,
            min_bucket, now,
            None if deadline_s is None else now + deadline_s,
            queue_span=queue_span,
            acct=fp.current() if fp is not None else None,
        )
        with self._lock:
            if self._closed:
                err = SchedulerClosedError("device scheduler is shut down")
                queue_span.set_error(err)
                queue_span.finish()
                raise err
            if self._queued_rows + len(rows) > self._max_queue_rows:
                _metrics().counter("serving.rejected").inc()
                slo = active_slo()
                if slo is not None:
                    # an admission reject is an SLO error for its class
                    # with NO latency sample — the request never ran, and
                    # instant rejects must not read as a perfect p99
                    slo.observe(priority, None, error=True)
                err = SchedulerSaturatedError(
                    f"serving queue full ({self._queued_rows} rows queued, "
                    f"bound {self._max_queue_rows})"
                )
                queue_span.set_error(err)
                queue_span.finish()
                raise err
            self._queues[priority].append(req)
            self._queued_rows += len(rows)
            dt = now - self._arrival_last
            if dt > 0:
                alpha = 1.0 - math.exp(-dt / 5.0)
                self._arrival_rate += alpha * (len(rows) / dt - self._arrival_rate)
                self._arrival_last = now
            self._lock.notify_all()
        m = _metrics()
        m.meter("serving.requests").mark()
        m.meter("serving.rows").mark(len(rows))
        return fut

    def submit_transactions(
        self,
        stxs: list,
        allowed_missing: list | None = None,
        *,
        priority: str = SERVICE,
        deadline_s: float | None = None,
        use_device: bool | None = None,
        min_bucket: int | None = None,
        trace=None,
    ) -> Future:
        """Enqueue the signature half of a batched transaction check; the
        Future resolves to a ``BatchVerifyReport`` with verdicts identical
        to ``verifier.check_transactions`` (same row algebra, shared
        code)."""
        from corda_tpu.verifier.batch import (
            flatten_signature_rows,
            tx_report_from_mask,
        )

        if allowed_missing is None:
            allowed_missing = [set()] * len(stxs)
        if len(allowed_missing) != len(stxs):
            raise ValueError("allowed_missing length mismatch")
        rows, row_tx, row_sig = flatten_signature_rows(stxs)
        inner = self.submit_rows(
            rows, priority=priority, deadline_s=deadline_s,
            use_device=use_device, min_bucket=min_bucket, trace=trace,
        )
        out: Future = Future()

        def finish(f: Future):
            try:
                rr: RowResult = f.result()
                report = tx_report_from_mask(
                    stxs, allowed_missing, rr.mask, row_tx, row_sig,
                    rr.n_device, batch_seq=rr.batch_seq, device=rr.device,
                )
                _complete(out, result=report)
            except Exception as e:
                _complete(out, error=e)

        inner.add_done_callback(finish)
        return out

    # ---------------------------------------------------------- test hooks
    def pause(self) -> None:
        """Hold batch assembly (deterministic coalescing in tests)."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            self._lock.notify_all()

    # ------------------------------------------------------------ dispatch
    def _has_work_locked(self) -> bool:
        return any(self._queues[c] for c in _CLASSES)

    def _dispatch_loop(self) -> None:
        while True:
            with self._lock:
                while not self._closed and (
                    self._paused or not self._has_work_locked()
                ):
                    self._lock.wait(timeout=0.5)
                if self._closed and not self._has_work_locked():
                    break
                batch, shed = self._assemble_locked()
            if shed:
                self._fail_shed(shed)
            if not batch:
                continue
            # bounded in-flight pipeline: wait for a free device slot
            # BEFORE enqueueing — the natural dispatch-rate brake (the
            # collector frees slots as batches settle, in whatever order
            # they complete). Host-only batches skip the wait: they
            # settle on the host pool and must not queue behind slow
            # device kernels.
            if any(r.use_device for r in batch):
                late: list = []
                with self._lock:
                    while self._inflight >= self._depth:
                        self._lock.wait(timeout=0.5)
                        # deadlines keep ticking while the batch parks
                        # at the slot wait: shed expired members on
                        # every wake-up rather than dispatching late
                        # with device time nobody waits for; a
                        # no-longer-device remainder abandons the wait
                        now = time.monotonic()
                        expired = [r for r in batch if (
                            r.deadline is not None and now > r.deadline
                        )]
                        if expired:
                            late += expired
                            batch = [r for r in batch if r not in expired]
                            if not any(r.use_device for r in batch):
                                break
                if late:
                    self._fail_shed(late)
                if not batch:
                    continue
            try:
                entry = self._dispatch(batch)
            except Exception as e:  # defensive: never lose futures
                for r in batch:
                    _complete(r.future, error=e)
                continue
            if entry is None:
                continue  # host-only batch: settling on the host pool
            with self._lock:
                self._inflight += 1
            # hedge arming comes AFTER the slot accounting: a deadline
            # that fired in between would otherwise release a slot that
            # was never counted
            self._arm_hedge(entry)
            self._inflight_q.put(entry)
        self._inflight_q.put(None)

    @staticmethod
    def _fail_shed(requests: list) -> None:
        """Complete shed requests with DeadlineExceededError (counted,
        spans landed) — shared by assembly-time and slot-wait shedding."""
        _metrics().counter("serving.shed").inc(len(requests))
        slo = active_slo()
        fp = active_flowprof()
        now = time.monotonic()
        for r in requests:
            if slo is not None:
                # a shed IS the SLO signal: the request aged out
                slo.observe(r.priority, now - r.enqueued_at, error=True)
            if fp is not None:
                # the shed request's whole life was queue wait: book it to
                # the owning flow's phase ledger so propagated-deadline
                # sheds show up in the waterfall, not as missing wall
                fp.add(r.acct, "queue_wait", now - r.enqueued_at)
            err = DeadlineExceededError(
                "request shed: deadline passed while queued"
            )
            r.queue_span.set_error(err)
            r.queue_span.finish()
            _complete(r.future, error=err)

    def _requeue_failed(self, dev_reqs: list) -> list:
        """Deterministic re-dispatch (resilience): put failed device
        requests back at the FRONT of their priority queues with their
        ORIGINAL arrival times — re-assembly orders them exactly where
        they were, so a quarantine-triggering failure costs one retry,
        not queue position. Verification is pure, so re-execution is
        safe; the futures stay pending (completed exactly once by
        whichever dispatch finally settles them). Returns the requests
        that exhausted their redispatch budget — the caller host-fails
        them over like the legacy path."""
        pol = self._resilience
        retry = [r for r in dev_reqs
                 if r.redispatches < pol.redispatch_limit]
        rest = [r for r in dev_reqs
                if r.redispatches >= pol.redispatch_limit]
        if retry:
            _metrics().counter("serving.redispatch").inc(len(retry))
            with self._lock:
                for r in reversed(retry):
                    r.redispatches += 1
                    # its queue wait was already recorded at dispatch;
                    # the retry must not double-finish the span
                    r.queue_span = NOOP_SPAN
                    self._queues[r.priority].appendleft(r)
                    self._queued_rows += len(r.rows)
                self._lock.notify_all()
        return rest

    # ---------------------------------------------------- mesh placement
    def _mesh_on(self) -> bool:
        """Lazily resolve the striping switch: explicit constructor
        value, else CORDA_TPU_MESH (1/0), else on exactly when more than
        one REAL accelerator is attached (the service-mesh activation
        rule — 8 virtual CPU devices stay single-chip unless a test
        opts in). Resolved once; only dispatches with device work reach
        here, so jax is about to be touched anyway."""
        if self._mesh is None:
            env = os.environ.get("CORDA_TPU_MESH", "").strip().lower()
            if env in ("1", "true", "on", "yes"):
                self._mesh = True
            elif env in ("0", "false", "off", "no"):
                self._mesh = False
            else:
                try:
                    import jax

                    self._mesh = (jax.default_backend() != "cpu"
                                  and len(jax.devices()) > 1)
                except Exception:
                    self._mesh = False
        return self._mesh

    def _ensure_devices(self) -> dict:
        if self._devices is None:
            import jax

            self._devices = {int(d.id): d for d in jax.devices()}
        return self._devices

    def _stripe_set(self) -> list[int]:
        """The eligible ordinals a batch may be placed on: every visible
        device minus devicemon's watchdog-evicted set minus ordinals the
        resilience policy blocks (quarantined or breaker-open —
        ``admit_ordinal`` is the counter-free read). Empty means the
        whole mesh is down: the caller host-routes."""
        try:
            ordinals = sorted(self._ensure_devices())
        except Exception:
            return []
        mon = active_devicemon()
        if mon is not None:
            try:
                bad = mon.unhealthy_ordinals()
                ordinals = [o for o in ordinals if o not in bad]
            except Exception:
                pass
        pol = self._resilience
        if pol is not None:
            ordinals = [o for o in ordinals if pol.admit_ordinal(o)]
        with self._lock:
            self._stripe_width = len(ordinals)
        return ordinals

    def mesh_stripe_width(self) -> int:
        """How many ordinals the scheduler is currently striping over
        (0 when mesh scheduling is off). Pipelined callers size their
        in-flight depth from this: a depth tuned for one chip underfills
        an 8-chip stripe — the notary's ``process_stream`` keeps at
        least one window in flight per stripe member."""
        if not self._mesh_on():
            return 0
        return len(self._stripe_set())

    def _place_locked(self, eligible: list[int]) -> int:
        """Power-of-two-choices placement (lock held): a rotating
        candidate (guaranteed coverage of the stripe) races the globally
        least-loaded one, and the batch lands on the smaller
        (in-flight depth, execute-wall EWMA) score. Reserves one depth
        unit on the winner — released exactly once when the batch
        settles (``_settle_entry``'s finally) or its dispatch raises."""
        self._place_seq += 1
        c1 = eligible[self._place_seq % len(eligible)]
        c2 = min(eligible, key=lambda o: (
            self._ord_inflight.get(o, 0), self._ord_ewma.get(o, 0.0),
        ))

        def score(o):
            return (self._ord_inflight.get(o, 0),
                    self._ord_ewma.get(o, 0.0))

        pick = c1 if score(c1) <= score(c2) else c2
        self._ord_inflight[pick] = self._ord_inflight.get(pick, 0) + 1
        depths = [self._ord_inflight.get(o, 0) for o in eligible]
        spread = max(depths) - min(depths)
        if spread > self._mesh_spread_max:
            self._mesh_spread_max = spread
        return pick

    def _ord_release(self, ordinal: int | None) -> None:
        """Return one reserved per-ordinal depth unit (no-op for None or
        a never-reserved ordinal — the single-chip path reserves
        nothing)."""
        if ordinal is None:
            return
        with self._lock:
            d = self._ord_inflight.get(ordinal, 0)
            if d > 0:
                self._ord_inflight[ordinal] = d - 1

    def _pick_sibling(self, exclude: int) -> int | None:
        """Least-loaded healthy ordinal OTHER than the stalled one — the
        hedge re-routes to a sibling chip before conceding to the host
        reference path. Reserves a depth unit on the pick;
        ``_settle_hedge_sibling`` releases it on every exit."""
        stripe = [o for o in self._stripe_set() if o != exclude]
        if not stripe:
            return None
        with self._lock:
            pick = min(stripe, key=lambda o: (
                self._ord_inflight.get(o, 0), self._ord_ewma.get(o, 0.0),
            ))
            self._ord_inflight[pick] = self._ord_inflight.get(pick, 0) + 1
        return pick

    def _mega_eligible(self, dev_rows, bucket, stripe) -> bool:
        """A mega-batch fuses one high-fill homogeneous ed25519 bucket
        over the WHOLE mesh: the shard_map step shards over every chip,
        so a single quarantined/evicted ordinal vetoes fusion (striping
        still covers the healthy remainder), and only the ed25519 shape
        carries the notary-commit collective."""
        if len(stripe) < 2:
            return False
        try:
            if len(stripe) != len(self._ensure_devices()):
                return False
        except Exception:
            return False
        if len(dev_rows) < self._megabatch_fill * bucket:
            return False
        from corda_tpu.crypto import EDDSA_ED25519_SHA512

        return all(
            getattr(k, "scheme_id", None) == EDDSA_ED25519_SHA512
            for k, _s, _m in dev_rows
        )

    def _dispatch_mega(self, dev_rows: list, bucket: int) -> _MeshPending:
        """Fuse one full bucket into a single shard_map mega-batch: every
        chip verifies its shard and the consumed-set delta (per-row tx
        digests) comes back all-gathered over ICI — the notary-commit
        collective built by ``distributed_verify_step``. Per-ordinal
        telemetry attribution is recorded inside the mesh verifier
        (``record_sharded_dispatch``), NOT here — recording both would
        double-count."""
        from corda_tpu.parallel.mesh import service_mesh_verifier

        keys = [k.encoded for k, _s, _m in dev_rows]
        sigs = [s for _k, s, _m in dev_rows]
        msgs = [m for _k, _s, m in dev_rows]
        mask, spent_all, total = service_mesh_verifier().dispatch_rows(
            keys, sigs, msgs, min_bucket=bucket,
            spent_hashes=_consumed_rows(msgs),
        )
        pending = _MeshPending(
            dev_rows, mask, spent_all, total, bucket=int(mask.shape[0]),
        )
        from corda_tpu.statestore import active_mega_screen

        screen = active_mega_screen()
        if screen is not None:
            # fuse the statestore's conflict screen into the same
            # dispatch round: probe the still-device-resident consumed
            # delta against the sharded table — device-to-device, no
            # host copy; the hit count settles with the batch
            # (docs/STATE_STORE.md §Serving fusion)
            try:
                pending.statestore_hits = screen(spent_all, len(dev_rows))
            except Exception:
                _metrics().counter("statestore.mega_screen_failed").inc()
        return pending

    # ------------------------------------------------------------- hedging
    def _arm_hedge(self, entry: _InFlight) -> None:
        """Give one dispatched device batch its in-flight deadline
        (execute-wall EWMA × hedge factor, via the policy) and hand it to
        the hedge monitor. No policy, no device, or no EWMA yet (a cold
        first dispatch may legally be a multi-minute compile) leaves the
        entry unarmed — the collector blocks on it like the legacy path."""
        pol = self._resilience
        if pol is None or entry.device is None:
            return
        with self._lock:
            ewma = self._latency_ewma
            # a batch touching any not-yet-settled (scheme, bucket) shape
            # may be paying its one-off compile: never hedge it (an EWMA
            # seeded by warm shapes says nothing about a cold compile)
            if not entry.compile_keys <= self._warm_keys:
                return
        deadline_s = pol.hedge_deadline_s(entry.device, ewma)
        if deadline_s is None:
            return
        with self._lock:
            if entry.winner is not None:
                return  # already settled: nothing left to hedge
            entry.deadline = entry.t0 + deadline_s
            self._hedge_entries.append(entry)
            self._lock.notify_all()

    def _hedge_loop(self) -> None:
        """Resilience-only monitor thread: wakes for the earliest armed
        in-flight deadline, hedges expired batches to the host pool, and
        runs any due canary probe (quarantine readmission / breaker
        half-open) — the scheduler's only periodic heartbeat."""
        while True:
            due: list[_InFlight] = []
            with self._lock:
                if self._closed and not self._hedge_entries:
                    break
                now = time.monotonic()
                nxt = None
                for e in list(self._hedge_entries):
                    if e.winner is not None:
                        self._hedge_entries.remove(e)
                    elif now >= e.deadline:
                        due.append(e)
                        self._hedge_entries.remove(e)
                    elif nxt is None or e.deadline < nxt:
                        nxt = e.deadline
                if not due:
                    timeout = (
                        0.2 if nxt is None
                        else min(max(nxt - now, 0.001), 0.2)
                    )
                    self._lock.wait(timeout=timeout)
            pol = self._resilience
            if pol is not None:
                pol.maybe_probe()
            for e in due:
                self._fire_hedge(e)

    def _fire_hedge(self, entry: _InFlight) -> None:
        """An in-flight batch blew its deadline with no settle: re-run it
        on the host reference path (first result wins) and release the
        device depth slot — a stalled dispatch must not park a pipeline
        slot forever. The device's late readback, if it ever lands, is
        discarded by the collector."""
        with self._lock:
            if entry.winner is not None:
                return  # settled between dequeue and fire
            entry.hedged = True
            if not entry.slot_freed:
                entry.slot_freed = True
                self._inflight -= 1
            self._lock.notify_all()
        _metrics().counter("serving.hedge.fired").inc()
        entry.span.set_attr("hedged", True)
        pol = self._resilience
        if pol is not None and entry.device is not None:
            pol.on_hedge_fired(entry.device)
        # mesh mode: re-route to a SIBLING chip from the stripe set
        # before conceding to the host reference path — the mesh is
        # healthy even when one ordinal stalls. No sibling (single chip,
        # or the rest of the stripe is down) falls through to the host
        # leg exactly like PR 9.
        sibling = None
        if entry.device is not None and self._mesh_on():
            sibling = self._pick_sibling(entry.device)
        try:
            if sibling is not None:
                self._host_pool.submit(
                    self._settle_hedge_sibling, entry, sibling
                )
            else:
                self._host_pool.submit(self._settle_hedge_host, entry)
        except RuntimeError:
            if sibling is not None:
                self._ord_release(sibling)
            self._settle_hedge_host(entry)  # pool closed: settle inline

    def _settle_hedge_sibling(self, entry: _InFlight, ordinal: int) -> None:
        """The hedge's sibling leg: re-dispatch the stalled batch PINNED
        to a healthy sibling chip — rows settle on device, not on the
        host loop — before conceding to the host reference path. First
        result wins exactly as for the host leg: the original device's
        late readback may still claim first, in which case this result
        is dropped. Any sibling failure (or a second stall past its own
        hedge deadline) falls through to the host leg, so the batch is
        never worse off than the plain host hedge. The caller reserved
        one depth unit on ``ordinal``; every exit releases it."""
        m = _metrics()
        m.counter("serving.hedge.rerouted").inc()
        mon = active_devicemon()
        dispatched = False
        t0 = time.monotonic()
        try:
            from corda_tpu.verifier.batch import dispatch_signature_rows

            device = self._ensure_devices()[ordinal]
            dev_rows = [row for r in entry.requests for row in r.rows]
            floor = 0
            for r in entry.requests:
                if r.min_bucket:
                    floor = max(floor, r.min_bucket)
            bucket = self._shapes.bucket_for(len(dev_rows), floor=floor)
            pending = dispatch_signature_rows(
                dev_rows, use_device=True, min_bucket=bucket,
                device=device,
            )
            padded = getattr(pending, "padded_lanes", 0) or len(dev_rows)
            if mon is not None:
                mon.record_dispatch(
                    ordinal, rows=len(dev_rows), padded_lanes=padded
                )
            dispatched = True
            pol = self._resilience
            deadline_s = (
                pol.hedge_deadline_s(ordinal, self._latency_ewma)
                if pol is not None else None
            )
            deadline = None if deadline_s is None else t0 + deadline_s
            while not _pending_ready(pending):
                if deadline is not None and time.monotonic() >= deadline:
                    raise ServingError(
                        f"sibling ordinal {ordinal} stalled too"
                    )
                time.sleep(0.005)
            mask = pending.collect().astype(bool, copy=False)
            wall = time.monotonic() - t0
            if mon is not None:
                mon.record_settle(ordinal, wall)
        except Exception:
            if mon is not None and dispatched:
                mon.record_settle(
                    ordinal, time.monotonic() - t0, ok=False, ewma=False
                )
            self._ord_release(ordinal)
            self._settle_hedge_host(entry)
            return
        self._ord_release(ordinal)
        with self._lock:
            if entry.winner is not None:
                return  # the original device landed first: it won
            entry.winner = "sibling"
        m.counter("serving.hedge.won_sibling").inc()
        entry.span.set_attr("hedge_winner", "sibling")
        pol = self._resilience
        if pol is not None:
            if entry.device is not None:
                # the loss lands on the ORIGINAL ordinal's breaker; the
                # sibling's clean settle is its own healthy evidence
                pol.on_hedge_won_sibling(entry.device)
            pol.on_settle_ok(ordinal)
        on_device = getattr(pending, "device_mask", None)
        slo = active_slo()
        fp = active_flowprof()
        now = time.monotonic()
        k = 0
        for r in entry.requests:
            n = len(r.rows)
            nd = (int(on_device[k:k + n].sum())
                  if on_device is not None else 0)
            if slo is not None:
                slo.observe(r.priority, now - r.enqueued_at)
            if fp is not None:
                # the hedge's sibling leg won: device wall = the re-
                # dispatch's wall (the stalled original lost the race)
                fp.add(r.acct, "device_execute", wall)
            _complete(r.future, result=RowResult(
                mask[k:k + n], nd, entry.seq, device=ordinal,
            ))
            k += n

    def _settle_hedge_host(self, entry: _InFlight) -> None:
        """The hedge's host leg: re-verify every request on the host
        reference path, then claim the win — unless the device settled
        while we verified, in which case its (identical, verification is
        pure) verdicts already completed the futures and this result is
        simply dropped."""
        from corda_tpu.crypto import is_valid

        outcomes: list = []  # (mask, error, host-verify wall) per request
        for r in entry.requests:
            t_verify = time.monotonic()
            try:
                outcomes.append((np.array(
                    [is_valid(k, s, m) for k, s, m in r.rows], dtype=bool
                ), None, time.monotonic() - t_verify))
            except Exception as e:
                outcomes.append((None, e, time.monotonic() - t_verify))
        with self._lock:
            if entry.winner is not None:
                return  # the device landed first: it won the race
            entry.winner = "host"
        _metrics().counter("serving.hedge.won_host").inc()
        entry.span.set_attr("hedge_winner", "host")
        pol = self._resilience
        if pol is not None and entry.device is not None:
            pol.on_hedge_won_host(entry.device)
        slo = active_slo()
        fp = active_flowprof()
        now = time.monotonic()
        for r, (mask, err, verify_wall) in zip(entry.requests, outcomes):
            if fp is not None:
                # the hedge's host leg won: the member flows' requests
                # settled on host verification, not device execute
                fp.add(r.acct, "host_verify", verify_wall)
            if err is None:
                if slo is not None:
                    slo.observe(r.priority, now - r.enqueued_at)
                _complete(r.future, result=RowResult(mask, 0, entry.seq))
            else:
                if slo is not None:
                    slo.observe(
                        r.priority, now - r.enqueued_at, error=True
                    )
                _complete(r.future, error=err)

    def _assemble_locked(self) -> tuple[list, list]:
        """Shed over-deadline work, then assemble one batch under the
        adaptive row cap honoring per-class reserved shares. Requests are
        never split across batches."""
        now = time.monotonic()
        shed: list = []
        for q in self._queues.values():
            if not q:
                continue
            keep = [r for r in q if not (
                r.deadline is not None and now > r.deadline
            )]
            if len(keep) != len(q):
                for r in q:
                    if r.deadline is not None and now > r.deadline:
                        shed.append(r)
                        self._queued_rows -= len(r.rows)
                q.clear()
                q.extend(keep)
        # adaptive cap: expected arrivals during one device round trip,
        # clamped so small queues still coalesce fully and huge queues
        # split into pipeline-depth chunks
        target = self._arrival_rate * max(self._latency_ewma, 1e-4)
        cap = int(min(self._max_batch_rows,
                      max(self._min_batch_rows, target)))
        batch: list = []
        taken = 0

        def pop_into(cls):
            nonlocal taken
            r = self._queues[cls].popleft()
            self._queued_rows -= len(r.rows)
            batch.append(r)
            taken += len(r.rows)

        # phase 1: reserved share per class (an oversize first request is
        # admitted whole — requests never split)
        for cls in _CLASSES:
            share = max(1, int(cap * _RESERVED[cls]))
            used = 0
            q = self._queues[cls]
            while q and taken < cap and (
                used == 0 or used + len(q[0].rows) <= share
            ):
                used += len(q[0].rows)
                pop_into(cls)
        # phase 2: leftover capacity fills oldest-first across classes
        while taken < cap:
            live = [c for c in _CLASSES if self._queues[c]]
            if not live:
                break
            cls = min(live, key=lambda c: self._queues[c][0].enqueued_at)
            if batch and taken + len(self._queues[cls][0].rows) > cap:
                break
            pop_into(cls)
        return batch, shed

    def _dispatch(self, batch: list) -> "_InFlight | None":
        """Async half: partition requests by device routing, enqueue ONE
        shape-bucketed device dispatch for the device rows (no readback),
        and hand host-routed requests to the host pool. Returns the
        in-flight device entry, or None for a host-only batch."""
        t0 = time.monotonic()
        m = _metrics()
        with self._lock:
            self._seq += 1
            seq = self._seq
        wait_t = m.timer("serving.wait_s")
        fp = active_flowprof()
        for r in batch:
            # exemplar: a sampled request's trace id rides its reservoir
            # sample, so an exposed p99 quantile can name the trace that
            # produced it (NOOP spans carry "" → no exemplar)
            wait_t.update(t0 - r.enqueued_at,
                          exemplar=r.queue_span.trace_id or None)
            if fp is not None:
                fp.add(r.acct, "queue_wait", t0 - r.enqueued_at)
        m.meter("serving.batches").mark()
        # occupancy histogram: requests coalesced per batch (the Timer is
        # a generic histogram; values are counts, not seconds)
        m.timer("serving.batch_occupancy").update(float(len(batch)))
        # one serving.batch span per dispatched batch: parented under the
        # FIRST sampled member's queue span (which makes a lone flow's
        # trace a clean chain) and LINKED to every sampled member — the
        # fan-in of cross-client coalescing that a parent tree alone
        # cannot express. Queue-wait spans close here: the wait is over.
        batch_span = NOOP_SPAN
        for r in batch:
            qs = r.queue_span
            if qs.sampled:
                qs.set_attr("batch_seq", seq)
                if not batch_span.sampled:
                    batch_span = tracer().start(
                        SPAN_SERVING_BATCH, qs,
                        attrs={"batch_seq": seq, "n_requests": len(batch)},
                    )
                batch_span.add_link(qs)
            qs.finish()
        dev_reqs = [r for r in batch if r.use_device]
        host_reqs = [r for r in batch if not r.use_device]
        pending = None
        dev_rows: list = []
        dev_map: list = []
        ordinal = None
        placed = False
        mesh_on = False
        stripe: list = []
        pol = self._resilience
        if dev_reqs:
            mesh_on = self._mesh_on()
        if dev_reqs and mesh_on:
            # mesh routing gate: the stripe set already excludes
            # quarantined / breaker-open / watchdog-evicted ordinals, so
            # placement below only ever picks admissible chips; an EMPTY
            # stripe means every ordinal is down — whole-mesh host
            # routing (the per-device breakers' collective OPEN)
            stripe = self._stripe_set()
            if not stripe:
                m.counter("serving.mesh.no_eligible").inc()
                batch_span.set_attr("resilience_host_routed", True)
                host_reqs = host_reqs + dev_reqs
                dev_reqs = []
        elif dev_reqs and pol is not None:
            # single-chip resilience gate, consulted on EVERY dispatch:
            # an open breaker or a quarantined ordinal routes the whole
            # device cohort to the host pool — zero device enqueues, the
            # verdicts identical by the shared host reference path. The
            # ordinal is resolved ONCE here and threaded through: the
            # success attribution and the failure strike below must name
            # the same ordinal this gate admitted.
            ordinal = default_device_ordinal()
            if not pol.admit_device(ordinal):
                batch_span.set_attr("resilience_host_routed", True)
                host_reqs = host_reqs + dev_reqs
                dev_reqs = []
        if dev_reqs:
            floor = 0
            for i, r in enumerate(dev_reqs):
                if r.min_bucket:
                    floor = max(floor, r.min_bucket)
                for j, row in enumerate(r.rows):
                    dev_rows.append(row)
                    dev_map.append((i, j))
            from corda_tpu.faultinject import check_site
            from corda_tpu.verifier.batch import dispatch_signature_rows

            bucket = self._shapes.bucket_for(len(dev_rows), floor=floor)
            mega = False
            mesh_ordinals: tuple = ()
            device = None
            if mesh_on:
                mega = self._mega_eligible(dev_rows, bucket, stripe)
                if mega:
                    mesh_ordinals = tuple(sorted(self._ensure_devices()))
                else:
                    with self._lock:
                        ordinal = self._place_locked(stripe)
                    placed = True
                    device = self._ensure_devices().get(ordinal)
            elif ordinal is None:
                # no resilience gate ran: resolve the attribution ordinal
                # once, up front (single-chip dispatch runs on the
                # backend default)
                ordinal = default_device_ordinal()
            # each scheme bucket compiles independently — AND device
            # placement is part of the executable (pinning a warm shape
            # to a new ordinal recompiles): the shape keys this dispatch
            # may have to compile, checked warm before hedging
            compile_keys = frozenset(
                (getattr(k, "scheme_id", None), bucket,
                 "mesh" if mega else ordinal)
                for k, _s, _m in dev_rows
            )

            def lanes_of(pending):
                # ground truth from the dispatch itself: each scheme
                # bucket pads independently, and PendingRows sums the
                # lanes the kernels REALLY ran (a shape-table estimate
                # would under-count mixed-scheme batches)
                return getattr(pending, "padded_lanes", 0) or len(dev_rows)

            try:
                # the scheduler-level fail site: a FaultPlan can force the
                # WHOLE batch onto the host reference path deterministically.
                # The batch span is ACTIVATED around the dispatch so a fault
                # injected here (or at the nested verifier.device site)
                # stamps this batch's trace id onto its chaos event —
                # without it the dispatcher thread has no ambient context.
                # stamp_span lets profiled kernels inside the dispatch tag
                # this batch's span with their kernel/bucket (no-op unless
                # the profiler is on AND the span is sampled)
                with tracer().activate(batch_span), stamp_span(batch_span):
                    # check_site returns an injected STALL delay (the
                    # stall_sites fault mode): grafted onto the pending
                    # below, so the batch dispatches normally and then
                    # sits not-ready in flight — the hedge path's shape.
                    # A fused mega-batch has its own site: an injected
                    # failure there is a WHOLE-STRIPE failure.
                    if mega:
                        stall_s = check_site("serving.mesh_dispatch")
                    else:
                        stall_s = check_site("serving.dispatch")
                    prof = active_profiler()
                    # the device kwarg only travels when placement pinned
                    # an ordinal: the single-chip path keeps the original
                    # call shape (monkeypatched fakes predate the kwarg)
                    kw = {"min_bucket": bucket}
                    if device is not None:
                        kw["device"] = device
                    if mega:
                        pending = self._dispatch_mega(dev_rows, bucket)
                    elif prof is None:
                        pending = dispatch_signature_rows(
                            dev_rows, use_device=True, **kw
                        )
                    else:
                        pending = prof.profile(
                            KERNEL_SERVING_DISPATCH,
                            lambda: dispatch_signature_rows(
                                dev_rows, use_device=True, **kw
                            ),
                            rows=len(dev_rows), bucket=lanes_of,
                        )
                if stall_s:
                    injector = getattr(pending, "inject_stall", None)
                    if injector is not None:
                        injector(stall_s)
                # bucket-induced waste, visible with the profiler OFF:
                # wasted lanes per dispatch (histogram) + the cumulative
                # fill-ratio gauge registered in _register_process_gauges
                padded = lanes_of(pending)
                m.timer("serving.batch_pad_waste").update(
                    float(padded - len(dev_rows))
                )
                self._real_rows += len(dev_rows)
                self._padded_rows += padded
                if mega:
                    m.counter("serving.mesh.megabatch").inc()
                    m.counter("serving.mesh.megabatch_rows").inc(
                        len(dev_rows)
                    )
                    batch_span.set_attr("mesh_megabatch", True)
                    # per-ordinal attribution already recorded by the
                    # mesh verifier's sharded-dispatch helper
                else:
                    # per-chip attribution, on the ordinal resolved once
                    # above (placement, the resilience gate, or the
                    # backend default) — stamped on the span + result and
                    # fed to the per-device telemetry registry
                    if mesh_on:
                        m.counter("serving.mesh.striped").inc()
                    batch_span.set_attr("device", ordinal)
                    mon = active_devicemon()
                    if mon is not None:
                        mon.record_dispatch(
                            ordinal, rows=len(dev_rows), padded_lanes=padded
                        )
                    with self._lock:
                        self._ord_dispatches[ordinal] = (
                            self._ord_dispatches.get(ordinal, 0) + 1
                        )
            except Exception:
                if placed:
                    self._ord_release(ordinal)
                if mega:
                    # a whole-stripe failure has no single ordinal to
                    # blame: no strike, no requeue — the cohort fails
                    # over to the host reference path (identical
                    # verdicts), and the breakers learn per-ordinal from
                    # the striped traffic that follows
                    m.counter("serving.mesh.megabatch_failover").inc()
                    batch_span.set_attr("mesh_megabatch", True)
                else:
                    fail_ord = (ordinal if ordinal is not None
                                else default_device_ordinal())
                    mon = active_devicemon()
                    if mon is not None:
                        mon.record_failure(fail_ord)
                    if pol is not None:
                        # resilience path: strike the ordinal + breaker,
                        # then RE-DISPATCH — the requests re-enter the
                        # queue with their original arrival times and
                        # priority (no starvation: they go back to the
                        # FRONT), and only a request that exhausted its
                        # redispatch budget falls over to host like the
                        # legacy path
                        pol.on_dispatch_failure(fail_ord)
                        dev_reqs = self._requeue_failed(dev_reqs)
                if dev_reqs:
                    m.counter("serving.device_failover").inc()
                    batch_span.set_attr("device_failover", True)
                    host_reqs = host_reqs + dev_reqs
                dev_reqs, pending = [], None
        device_entry = bool(dev_reqs and pending is not None)
        batch_span.set_attr(
            "routing", "device" if device_entry else "host"
        )
        if host_reqs:
            # a host-only batch's span closes when the host pool settles
            # it; a mixed batch's span rides the device entry instead
            host_span = batch_span if not device_entry else NOOP_SPAN
            try:
                self._host_pool.submit(
                    self._settle_host, host_reqs, seq, host_span
                )
            except RuntimeError:
                self._settle_host(host_reqs, seq, host_span)  # pool closed
        if device_entry:
            return _InFlight(dev_reqs, pending, len(dev_rows), dev_map,
                             seq, t0, span=batch_span, device=ordinal,
                             compile_keys=compile_keys,
                             mesh_ordinals=mesh_ordinals)
        if not host_reqs:
            # the whole batch was re-dispatched: nobody else will finish
            # this span (no host settle, no device entry)
            batch_span.set_attr("redispatched", True)
            batch_span.finish()
        return None

    # ------------------------------------------------------------ collect
    @staticmethod
    def _settle_host(requests: list, seq: int, span=NOOP_SPAN) -> None:
        """Host reference path for host-routed (or failed-over) requests;
        runs on the host pool so a bulk host window never delays an
        unrelated batch's settlement."""
        from corda_tpu.crypto import is_valid

        slo = active_slo()
        fp = active_flowprof()
        for r in requests:
            try:
                t_verify = time.monotonic()
                mask = np.array(
                    [is_valid(k, s, m) for k, s, m in r.rows], dtype=bool
                )
                if fp is not None:
                    fp.add(
                        r.acct, "host_verify",
                        time.monotonic() - t_verify,
                    )
                if slo is not None:
                    slo.observe(
                        r.priority, time.monotonic() - r.enqueued_at
                    )
                _complete(r.future, result=RowResult(mask, 0, seq))
            except Exception as e:
                if slo is not None:
                    slo.observe(
                        r.priority, time.monotonic() - r.enqueued_at,
                        error=True,
                    )
                span.set_error(e)
                _complete(r.future, error=e)
        span.finish()

    def _collect_loop(self) -> None:
        # Settle in COMPLETION order, not dispatch order: with several
        # batches in flight (possibly different shape buckets), the one
        # that lands first should resolve its futures first — blocking on
        # the oldest dispatch would stack every later batch's settlement
        # behind the slowest kernel. When nothing is ready, block on the
        # oldest (the FIFO degenerate case, identical to the old loop).
        live: list[_InFlight] = []
        draining = False
        while True:
            while not draining:
                try:
                    entry = self._inflight_q.get(block=not live)
                except _queue.Empty:
                    break
                if entry is None:
                    draining = True
                else:
                    live.append(entry)
            if not live:
                if draining:
                    return
                continue
            entry = next(
                (e for e in live if _pending_ready(e.pending)), None
            )
            if entry is None:
                head = live[0]
                if head.hedged:
                    # stall-proof: NEVER wedge the collector on a batch
                    # whose hedge already fired — its futures are the
                    # host leg's job, and a permanently stalled readback
                    # would park every later batch's settle behind it.
                    # The late readback is reaped (collected, discarded,
                    # devicemon-settled) on the host pool instead.
                    live.remove(head)
                    self._reap_late(head)
                    continue
                if head.deadline is not None:
                    # an armed (hedgeable) batch: bounded wait, so the
                    # hedge firing mid-block cannot strand us — re-poll
                    # readiness and the hedged flag on a short tick
                    time.sleep(0.005)
                    continue
                entry = head  # legacy path: block on the oldest dispatch
            elif entry is not live[0]:
                _metrics().counter("serving.settle_reorder").inc()
            live.remove(entry)
            self._settle_entry(entry)

    def _reap_late(self, entry: "_InFlight") -> None:
        """Settle a hedged batch off the collector thread: the blocking
        readback (however late — possibly NEVER, for a truly wedged
        device) runs on a dedicated daemon thread, and the shared settle
        logic decides the race — the device may still win if the host
        leg has not claimed yet, otherwise the readback is discarded.
        NOT the host pool: a permanently stalled readback would park one
        of its fixed workers forever, wedging the host fallback path the
        hedge exists to provide (and shutdown's ``wait=True`` drain with
        it). The collector stays live either way; shutdown joins reapers
        with a BOUND, and a still-blocked one dies with the daemon flag
        at process exit."""
        t = threading.Thread(
            target=self._settle_entry, args=(entry,),
            name="serving-reap", daemon=True,
        )
        with self._lock:
            # prune finished reapers as we go: a long-lived scheduler on
            # a flapping device must not accumulate dead Thread objects
            self._reapers = [r for r in self._reapers if r.is_alive()]
            self._reapers.append(t)
        t.start()

    def _settle_entry(self, entry: "_InFlight") -> None:
        try:
            self._settle(entry)
        except Exception as e:
            with self._lock:
                # a hedged batch's device-side ERROR never claims the
                # win: the host leg is (or was) re-verifying and its good
                # verdicts must complete the futures — hedging exists
                # precisely to insure against this outcome
                ceded = entry.hedged and entry.winner != "device"
                if entry.winner is None and not entry.hedged:
                    entry.winner = "device"
            mon = active_devicemon()
            if mon is not None:
                if entry.device is not None:
                    mon.record_settle(
                        entry.device, time.monotonic() - entry.t0,
                        ok=False,
                    )
                elif entry.mesh_ordinals:
                    mon.record_sharded_settle(
                        entry.mesh_ordinals,
                        time.monotonic() - entry.t0, ok=False,
                    )
            pol = self._resilience
            if pol is not None and entry.device is not None:
                pol.on_dispatch_failure(entry.device)
            if ceded:
                _metrics().counter("serving.hedge.discarded").inc()
                entry.span.set_error(e)
                entry.span.set_attr("hedge_winner", "host")
                entry.span.finish()
                return
            slo = active_slo()
            if slo is not None:
                now = time.monotonic()
                for r in entry.requests:
                    slo.observe(
                        r.priority, now - r.enqueued_at, error=True
                    )
            entry.span.set_error(e)
            entry.span.finish()
            for r in entry.requests:
                _complete(r.future, error=e)
        finally:
            with self._lock:
                if not entry.slot_freed:
                    entry.slot_freed = True
                    self._inflight -= 1
                # return the per-ordinal depth unit the placement
                # reserved (no-op for unplaced single-chip/mega entries:
                # their count was never incremented)
                if entry.device is not None:
                    d = self._ord_inflight.get(entry.device, 0)
                    if d > 0:
                        self._ord_inflight[entry.device] = d - 1
                try:
                    self._hedge_entries.remove(entry)
                except ValueError:
                    pass
                self._lock.notify_all()

    def _settle(self, entry: _InFlight) -> None:
        masks = [np.zeros(len(r.rows), dtype=bool) for r in entry.requests]
        n_device = [0] * len(entry.requests)
        dev_mask = entry.pending.collect()
        on_device = getattr(
            entry.pending, "device_mask",
            np.zeros(entry.n_rows, dtype=bool),
        )
        for k, (i, j) in enumerate(entry.dev_map):
            masks[i][j] = bool(dev_mask[k])
            if on_device[k]:
                n_device[i] += 1
        latency = time.monotonic() - entry.t0
        m = _metrics()
        with self._lock:
            lost = entry.winner not in (None, "device")
            if entry.winner is None:
                entry.winner = "device"
            # the device completed this readback (even a hedge-lost late
            # one): its shapes are compiled — hedgeable from here on
            self._warm_keys |= entry.compile_keys
        m.timer("serving.batch_latency_s").update(
            latency, exemplar=entry.span.trace_id or None
        )
        mon = active_devicemon()
        if mon is not None:
            # the per-device completion heartbeat + execute-wall EWMA the
            # watchdog's straggler/stall rules evaluate — recorded even
            # for a hedge-lost batch (the device really did complete
            # now), but a lost readback's stall-inflated wall stays OUT
            # of the EWMA: folding it would grow the hedge deadline
            # (EWMA × factor) precisely on the device whose stalls it
            # exists to catch
            if entry.device is not None:
                mon.record_settle(entry.device, latency, ewma=not lost)
            elif entry.mesh_ordinals:
                # every shard shares the mega-batch's wall: the
                # collective synchronizes the mesh at the all-gather
                mon.record_sharded_settle(
                    entry.mesh_ordinals, latency, ewma=not lost
                )
        pol = self._resilience
        if lost:
            # the hedge's winning leg (host or sibling chip) already
            # completed every future: this is the loser's late readback,
            # discarded by contract (the verdicts are identical —
            # verification is pure — but the futures were completed
            # exactly once, by the winner)
            m.counter("serving.hedge.discarded").inc()
            entry.span.set_attr("hedge_winner", entry.winner)
            entry.span.set_attr("n_rows", entry.n_rows)
            entry.span.finish()
            return
        if pol is not None:
            if entry.device is not None:
                pol.on_settle_ok(entry.device)
            else:
                for o in entry.mesh_ordinals:
                    pol.on_settle_ok(o)
        if entry.hedged:
            m.counter("serving.hedge.won_device").inc()
            entry.span.set_attr("hedge_winner", "device")
        slo = active_slo()
        if slo is not None:
            now = time.monotonic()
            for r in entry.requests:
                # end-to-end (admission→settle) latency per priority
                # class — the windowed p99 the SLO objectives bound
                slo.observe(r.priority, now - r.enqueued_at)
        fp = active_flowprof()
        if fp is not None:
            # winner-only attribution (hedge-lost readbacks returned
            # above): each member flow waited the full batch wall
            for r in entry.requests:
                fp.add(r.acct, "device_execute", latency)
        entry.span.set_attr("n_rows", entry.n_rows)
        entry.span.set_attr("device_rows", int(sum(n_device)))
        entry.span.finish()
        with self._lock:
            self._latency_ewma = (
                latency if self._latency_ewma == 0.0
                else 0.7 * self._latency_ewma + 0.3 * latency
            )
            if entry.device is not None:
                # per-ordinal execute-wall EWMA feeding the placement
                # score — only clean settles reach this point (hedge-lost
                # readbacks returned above), so a stalling chip's
                # inflated walls never shrink its apparent cost
                prev = self._ord_ewma.get(entry.device, 0.0)
                self._ord_ewma[entry.device] = (
                    latency if prev == 0.0 else 0.7 * prev + 0.3 * latency
                )
        for r, mask, nd in zip(entry.requests, masks, n_device):
            _complete(r.future, result=RowResult(
                mask, nd, entry.seq, device=entry.device,
            ))

    # ----------------------------------------------------------- lifecycle
    def shutdown(self, timeout: float = 30.0) -> None:
        """Stop accepting work; QUEUED and in-flight requests all complete
        (with verdicts — the drain processes them — or with the dispatch
        error), waiting up to ``timeout`` per stage for a wedged device
        (clients' ``FuturePending.collect`` has its own bound for that
        case). Idempotent: a second shutdown is a no-op."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._paused = False
            self._lock.notify_all()
        self._dispatcher.join(timeout=timeout)
        # dispatcher is done submitting: let the host pool finish its
        # settlements, then the collector drain the device pipeline
        self._host_pool.shutdown(wait=True)
        self._collector.join(timeout=timeout)
        # bounded reaper drain: hedged batches' late readbacks usually
        # land here (their discard counters visible after shutdown), but
        # a truly wedged one cannot hang us — it is a daemon thread
        deadline = time.monotonic() + timeout
        with self._lock:
            reapers = list(self._reapers)
        for t in reapers:
            t.join(timeout=max(deadline - time.monotonic(), 0.0))
        if self._hedge is not None:
            self._hedge.join(timeout=timeout)
        if self._resilience is not None:
            self._resilience.detach(self)


class FuturePending:
    """Adapter giving a scheduler Future the two-phase ``collect()``
    surface of ``PendingTxCheck`` — drop-in for the notary/wavefront
    pipelines that enqueue now and block later. ``collect`` is BOUNDED:
    a wedged device (tunneled backend stall) surfaces as a ServingError
    the caller's per-window error handling turns into failed requests,
    never an indefinitely hung notary thread. The default leaves ample
    room for a cold remote compile (~3 min on the tunnel)."""

    __slots__ = ("_future", "_timeout")

    def __init__(self, future: Future, timeout: float = 600.0):
        self._future = future
        self._timeout = timeout

    def collect(self):
        try:
            return self._future.result(timeout=self._timeout)
        except _FutTimeout:
            raise ServingError(
                f"scheduler did not settle the batch within {self._timeout}s"
            ) from None


# ------------------------------------------------- process-global instance
#
# The device dispatch queue is a per-process resource (one backend, one
# compile cache), so production callers share ONE scheduler. Lazy
# creation; a shut-down global is transparently replaced on next access
# (tests shut it down freely).

_global: DeviceScheduler | None = None
_global_lock = threading.Lock()


def device_scheduler() -> DeviceScheduler:
    global _global
    with _global_lock:
        if _global is None or _global.closed:
            _global = DeviceScheduler()
        return _global


def configure_scheduler(**kwargs) -> DeviceScheduler:
    """Replace the process-global scheduler (shutting down the old one);
    node startup calls this with config-derived bounds."""
    global _global
    with _global_lock:
        old, _global = _global, None
    if old is not None:
        old.shutdown()
    with _global_lock:
        _global = DeviceScheduler(**kwargs)
        return _global


def shutdown_scheduler() -> None:
    global _global
    with _global_lock:
        sched, _global = _global, None
    if sched is not None:
        sched.shutdown()


def _register_process_gauges() -> None:
    """The ``serving.*`` gauges read THROUGH the global accessor rather
    than binding a scheduler instance: a shut-down/replaced scheduler is
    never pinned by the metric registry, a dead one reads as empty, and
    test-constructed local schedulers cannot hijack the production
    surface."""
    m = _metrics()

    def live(read):
        def fn():
            sched = _global
            if sched is None or sched.closed:
                return 0
            try:
                return read(sched)
            except Exception:
                return 0
        return fn

    m.gauge("serving.queue_rows", live(lambda s: s._queued_rows))
    m.gauge("serving.queue_depth", live(lambda s: sum(
        len(q) for q in s._queues.values()
    )))
    m.gauge("serving.inflight", live(lambda s: s._inflight))
    # mesh stripe health: how many ordinals the last stripe computation
    # found eligible, and the worst depth imbalance placement has seen
    # (acceptance bound: spread stays <= 2 under saturation)
    m.gauge("serving.mesh.stripe_width", live(lambda s: s._stripe_width))
    m.gauge("serving.mesh.depth_spread", live(lambda s: s._mesh_spread_max))
    # cumulative device-batch fill ratio (real rows / padded lanes): the
    # bucket-waste health read next to batch_occupancy — 1.0 before any
    # device dispatch (nothing padded means nothing wasted)
    m.gauge("serving.batch_fill_ratio", live(
        lambda s: (
            s._real_rows / s._padded_rows if s._padded_rows else 1.0
        )
    ))


_register_process_gauges()
