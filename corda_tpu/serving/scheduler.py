"""The process-global continuous-batching device scheduler.

BENCH_r05 showed the batch kernels fast (ed25519 13.5× host) while the
end-to-end latency paths lost to host because every caller owned its own
ad-hoc batching: the verifier's fixed-window flusher, the notary's submit
path, and flows dispatching singleton verifies — three queues in front of
one device, none aware of the others. This module is the single
device-dispatch subsystem in front of the kernels, the same scheduler
shape an inference-serving stack uses (continuous batching / request
coalescing; the reference's closest analogue is the Artemis verifier
queue feeding OutOfProcessTransactionVerifierService — one queue, many
producers).

Core loop (``_dispatch_loop``):

- requests enqueue with a PRIORITY CLASS (``INTERACTIVE`` flow verifies,
  ``SERVICE`` verifier traffic, ``BULK`` notary windows) and an optional
  deadline;
- the scheduler launches a batch whenever the device pipeline has a free
  slot and work is pending — there is NO fixed batching window, so a
  single request on an idle scheduler dispatches immediately instead of
  paying ``window_s``, and coalescing emerges from concurrency: whatever
  arrived while the previous batch was in flight forms the next batch;
- rows pad to a small set of compiled batch shapes (``shapes.py``, seeded
  from the block-sweep capture) so ragged sizes never trigger fresh XLA
  compiles mid-traffic;
- admission control is a bounded queue (reject with
  ``SchedulerSaturatedError``) and over-deadline work is SHED at batch
  assembly (``DeadlineExceededError``), with per-class reserved shares so
  a notary load spike cannot starve interactive flows (and vice versa);
- batch size adapts to observed arrival rate × device latency (EWMA),
  splitting a deep queue into pipeline-depth chunks instead of one giant
  serial batch;
- up to ``depth`` batches ride the device concurrently (dispatch is the
  async half of ``dispatch_signature_rows``; a separate collector thread
  harvests readbacks in COMPLETION order — ``serving.settle_reorder``
  counts out-of-order settles), preserving the round-trip overlap the
  notary and wavefront pipelines rely on.

Degradation contract: the ``serving.dispatch`` faultinject site sits in
front of every device dispatch; an injected (or real) dispatch failure
fails over the whole batch to the host reference path — identical
verdicts, ``serving.device_failover`` counted — and the per-bucket
``verifier.device`` site below still covers partial failures. Metrics
live in the process registry (``node_metrics()``) under ``serving.*``.
"""

from __future__ import annotations

import math
import queue as _queue
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutTimeout

import numpy as np

from corda_tpu.observability import (
    NOOP_SPAN,
    SPAN_SERVING_BATCH,
    SPAN_SERVING_QUEUE,
    tracer,
)
from corda_tpu.observability.devicemon import (
    active_devicemon,
    default_device_ordinal,
)
from corda_tpu.observability.profiler import (
    KERNEL_SERVING_DISPATCH,
    active_profiler,
    stamp_span,
)
from corda_tpu.observability.slo import active_slo

from .shapes import shape_table

# ------------------------------------------------------------ priorities

INTERACTIVE = "interactive"  # flow hot path: singleton / few-row verifies
SERVICE = "service"          # verifier service traffic
BULK = "bulk"                # notary windows / bulk resolve sweeps

_CLASSES = (INTERACTIVE, SERVICE, BULK)

# Reserved share of one batch per class. Classes are drained in this
# order up to their share; leftover capacity then fills OLDEST-FIRST
# across all classes, so neither a bulk spike (starving interactive) nor
# an interactive flood (starving bulk) can monopolize the device.
_RESERVED = {INTERACTIVE: 0.25, SERVICE: 0.25, BULK: 0.5}


class ServingError(Exception):
    """Base for scheduler-side request failures."""


class SchedulerClosedError(ServingError):
    pass


class SchedulerSaturatedError(ServingError):
    """Admission control: the bounded queue is full. Callers either
    surface the rejection or degrade to their direct dispatch path."""


class DeadlineExceededError(ServingError):
    """The request aged past its deadline before a device slot opened;
    it was shed instead of wasting a batch on an answer nobody waits for."""


class RowResult:
    """What a row-level submission resolves to: the (N,) bool verdict
    mask, how many rows actually settled on device, the sequence number
    of the device batch that served it (shared by every request
    coalesced into that batch — the cross-client coalescing witness),
    and the device ordinal the batch ran on (None for host-settled
    batches) — per-chip attribution even before the mesh scheduler."""

    __slots__ = ("mask", "n_device", "batch_seq", "device")

    def __init__(self, mask: np.ndarray, n_device: int, batch_seq: int,
                 device: int | None = None):
        self.mask = mask
        self.n_device = n_device
        self.batch_seq = batch_seq
        self.device = device


class _Request:
    __slots__ = ("rows", "future", "priority", "use_device", "min_bucket",
                 "enqueued_at", "deadline", "queue_span")

    def __init__(self, rows, future, priority, use_device, min_bucket,
                 enqueued_at, deadline, queue_span=NOOP_SPAN):
        self.rows = rows
        self.future = future
        self.priority = priority
        self.use_device = use_device
        self.min_bucket = min_bucket
        self.enqueued_at = enqueued_at
        self.deadline = deadline
        # open serving.queue span (NOOP for unsampled callers): starts at
        # admission on the submitting thread, finishes on the dispatcher
        # thread when the request leaves the queue for a batch
        self.queue_span = queue_span


class _InFlight:
    """One dispatched DEVICE batch: the async pending (no readback yet)
    plus the bookkeeping to slice verdicts back per request at collect
    time. Host-routed requests never enter the in-flight pipeline — they
    settle on the scheduler's host pool straight from dispatch."""

    __slots__ = ("requests", "pending", "n_rows", "dev_map", "seq", "t0",
                 "span", "device")

    def __init__(self, requests, pending, n_rows, dev_map, seq, t0,
                 span=NOOP_SPAN, device=None):
        self.requests = requests
        self.pending = pending
        self.n_rows = n_rows
        self.dev_map = dev_map      # (request index, row offset) per dev row
        self.seq = seq
        self.t0 = t0
        self.span = span            # serving.batch span, finished at settle
        self.device = device        # ordinal the dispatch ran on


def _metrics():
    from corda_tpu.node.monitoring import node_metrics

    return node_metrics()


def _pending_ready(pending) -> bool:
    """Non-blocking probe: has this in-flight batch's device work
    finished? Unknown pending types read as not-ready so the collector
    falls back to the FIFO blocking path for them."""
    probe = getattr(pending, "ready", None)
    if probe is None:
        return False
    try:
        return bool(probe())
    except Exception:
        return False


def _complete(future: Future, result=None, error: Exception | None = None):
    """Complete tolerating caller-side cancellation."""
    try:
        if error is None:
            future.set_result(result)
        else:
            future.set_exception(error)
    except Exception:
        pass


class DeviceScheduler:
    """One continuous-batching loop over the signature-verification
    kernels. Construct directly for tests; production code shares the
    process-global instance via ``device_scheduler()``."""

    def __init__(
        self,
        *,
        use_device_default: bool = True,
        max_batch_rows: int | None = None,
        min_batch_rows: int = 256,
        max_queue_rows: int = 131072,
        depth: int = 3,
        host_workers: int = 4,
        shapes=None,
    ):
        # `shapes`: an explicit ShapeTable override (tests and the smoke
        # harness pin small pad buckets to reuse already-compiled shapes)
        self._shapes = shapes or shape_table()
        self._use_device_default = use_device_default
        self._max_batch_rows = max_batch_rows or self._shapes.max_bucket
        self._min_batch_rows = min_batch_rows
        self._max_queue_rows = max_queue_rows
        self._lock = threading.Condition()
        self._queues: dict[str, deque] = {c: deque() for c in _CLASSES}
        self._queued_rows = 0
        self._closed = False
        self._paused = False            # test hook: hold assembly
        self._seq = 0
        # dispatcher→collector handoff; the depth bound lives on the
        # _inflight counter (waited on BEFORE device enqueue), not on the
        # queue, so the collector may hold several batches and settle
        # them in COMPLETION order without widening the device pipeline
        self._depth = max(1, depth)
        self._inflight_q: _queue.Queue = _queue.Queue()
        self._inflight = 0
        # host-routed rows settle here, off the device collector thread —
        # a bulk host window must not delay an unrelated device batch's
        # (or another host request's) completion
        self._host_pool = ThreadPoolExecutor(
            max_workers=host_workers, thread_name_prefix="serving-host"
        )
        # cumulative real-vs-padded device lanes: the fill-ratio gauge
        # (dispatcher-thread-only writes; read racily by the gauge)
        self._real_rows = 0
        self._padded_rows = 0
        # EWMA state: arrival rate (rows/s, ~5 s horizon) and per-batch
        # device latency — their product is the expected arrivals during
        # one round trip, i.e. the natural adaptive batch size
        self._arrival_rate = 0.0
        self._arrival_last = time.monotonic()
        self._latency_ewma = 0.0
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="serving-dispatch", daemon=True
        )
        self._collector = threading.Thread(
            target=self._collect_loop, name="serving-collect", daemon=True
        )
        self._dispatcher.start()
        self._collector.start()

    # ------------------------------------------------------------- submit
    @property
    def closed(self) -> bool:
        return self._closed

    def submit_rows(
        self,
        rows: list[tuple],
        *,
        priority: str = SERVICE,
        deadline_s: float | None = None,
        use_device: bool | None = None,
        min_bucket: int | None = None,
        trace=None,
    ) -> Future:
        """Enqueue (PublicKey, signature, message) rows; the Future
        resolves to a ``RowResult``. Raises ``SchedulerClosedError`` /
        ``SchedulerSaturatedError`` synchronously (admission control
        rejects at the door, it never queues doomed work).

        ``trace`` is an explicit parent ``TraceContext``/``Span`` for
        callers submitting from a thread that is not the traced request's
        (the notary flusher); same-thread callers inherit the activated
        context automatically. Sampled requests get a ``serving.queue``
        span covering admission→dispatch."""
        if priority not in _CLASSES:
            raise ValueError(f"unknown priority class {priority!r}")
        rows = list(rows)
        fut: Future = Future()
        if not rows:
            fut.set_result(RowResult(np.zeros(0, dtype=bool), 0, -1))
            return fut
        trc = tracer()
        queue_span = trc.start(
            SPAN_SERVING_QUEUE,
            trace if trace is not None else trc.current(),
            attrs={"priority": priority, "rows": len(rows)},
        )
        now = time.monotonic()
        req = _Request(
            rows, fut, priority,
            self._use_device_default if use_device is None else use_device,
            min_bucket, now,
            None if deadline_s is None else now + deadline_s,
            queue_span=queue_span,
        )
        with self._lock:
            if self._closed:
                err = SchedulerClosedError("device scheduler is shut down")
                queue_span.set_error(err)
                queue_span.finish()
                raise err
            if self._queued_rows + len(rows) > self._max_queue_rows:
                _metrics().counter("serving.rejected").inc()
                slo = active_slo()
                if slo is not None:
                    # an admission reject is an SLO error for its class
                    # with NO latency sample — the request never ran, and
                    # instant rejects must not read as a perfect p99
                    slo.observe(priority, None, error=True)
                err = SchedulerSaturatedError(
                    f"serving queue full ({self._queued_rows} rows queued, "
                    f"bound {self._max_queue_rows})"
                )
                queue_span.set_error(err)
                queue_span.finish()
                raise err
            self._queues[priority].append(req)
            self._queued_rows += len(rows)
            dt = now - self._arrival_last
            if dt > 0:
                alpha = 1.0 - math.exp(-dt / 5.0)
                self._arrival_rate += alpha * (len(rows) / dt - self._arrival_rate)
                self._arrival_last = now
            self._lock.notify_all()
        m = _metrics()
        m.meter("serving.requests").mark()
        m.meter("serving.rows").mark(len(rows))
        return fut

    def submit_transactions(
        self,
        stxs: list,
        allowed_missing: list | None = None,
        *,
        priority: str = SERVICE,
        deadline_s: float | None = None,
        use_device: bool | None = None,
        min_bucket: int | None = None,
        trace=None,
    ) -> Future:
        """Enqueue the signature half of a batched transaction check; the
        Future resolves to a ``BatchVerifyReport`` with verdicts identical
        to ``verifier.check_transactions`` (same row algebra, shared
        code)."""
        from corda_tpu.verifier.batch import (
            flatten_signature_rows,
            tx_report_from_mask,
        )

        if allowed_missing is None:
            allowed_missing = [set()] * len(stxs)
        if len(allowed_missing) != len(stxs):
            raise ValueError("allowed_missing length mismatch")
        rows, row_tx, row_sig = flatten_signature_rows(stxs)
        inner = self.submit_rows(
            rows, priority=priority, deadline_s=deadline_s,
            use_device=use_device, min_bucket=min_bucket, trace=trace,
        )
        out: Future = Future()

        def finish(f: Future):
            try:
                rr: RowResult = f.result()
                report = tx_report_from_mask(
                    stxs, allowed_missing, rr.mask, row_tx, row_sig,
                    rr.n_device, batch_seq=rr.batch_seq, device=rr.device,
                )
                _complete(out, result=report)
            except Exception as e:
                _complete(out, error=e)

        inner.add_done_callback(finish)
        return out

    # ---------------------------------------------------------- test hooks
    def pause(self) -> None:
        """Hold batch assembly (deterministic coalescing in tests)."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            self._lock.notify_all()

    # ------------------------------------------------------------ dispatch
    def _has_work_locked(self) -> bool:
        return any(self._queues[c] for c in _CLASSES)

    def _dispatch_loop(self) -> None:
        while True:
            with self._lock:
                while not self._closed and (
                    self._paused or not self._has_work_locked()
                ):
                    self._lock.wait(timeout=0.5)
                if self._closed and not self._has_work_locked():
                    break
                batch, shed = self._assemble_locked()
            if shed:
                self._fail_shed(shed)
            if not batch:
                continue
            # bounded in-flight pipeline: wait for a free device slot
            # BEFORE enqueueing — the natural dispatch-rate brake (the
            # collector frees slots as batches settle, in whatever order
            # they complete). Host-only batches skip the wait: they
            # settle on the host pool and must not queue behind slow
            # device kernels.
            if any(r.use_device for r in batch):
                late: list = []
                with self._lock:
                    while self._inflight >= self._depth:
                        self._lock.wait(timeout=0.5)
                        # deadlines keep ticking while the batch parks
                        # at the slot wait: shed expired members on
                        # every wake-up rather than dispatching late
                        # with device time nobody waits for; a
                        # no-longer-device remainder abandons the wait
                        now = time.monotonic()
                        expired = [r for r in batch if (
                            r.deadline is not None and now > r.deadline
                        )]
                        if expired:
                            late += expired
                            batch = [r for r in batch if r not in expired]
                            if not any(r.use_device for r in batch):
                                break
                if late:
                    self._fail_shed(late)
                if not batch:
                    continue
            try:
                entry = self._dispatch(batch)
            except Exception as e:  # defensive: never lose futures
                for r in batch:
                    _complete(r.future, error=e)
                continue
            if entry is None:
                continue  # host-only batch: settling on the host pool
            with self._lock:
                self._inflight += 1
            self._inflight_q.put(entry)
        self._inflight_q.put(None)

    @staticmethod
    def _fail_shed(requests: list) -> None:
        """Complete shed requests with DeadlineExceededError (counted,
        spans landed) — shared by assembly-time and slot-wait shedding."""
        _metrics().counter("serving.shed").inc(len(requests))
        slo = active_slo()
        now = time.monotonic()
        for r in requests:
            if slo is not None:
                # a shed IS the SLO signal: the request aged out
                slo.observe(r.priority, now - r.enqueued_at, error=True)
            err = DeadlineExceededError(
                "request shed: deadline passed while queued"
            )
            r.queue_span.set_error(err)
            r.queue_span.finish()
            _complete(r.future, error=err)

    def _assemble_locked(self) -> tuple[list, list]:
        """Shed over-deadline work, then assemble one batch under the
        adaptive row cap honoring per-class reserved shares. Requests are
        never split across batches."""
        now = time.monotonic()
        shed: list = []
        for q in self._queues.values():
            if not q:
                continue
            keep = [r for r in q if not (
                r.deadline is not None and now > r.deadline
            )]
            if len(keep) != len(q):
                for r in q:
                    if r.deadline is not None and now > r.deadline:
                        shed.append(r)
                        self._queued_rows -= len(r.rows)
                q.clear()
                q.extend(keep)
        # adaptive cap: expected arrivals during one device round trip,
        # clamped so small queues still coalesce fully and huge queues
        # split into pipeline-depth chunks
        target = self._arrival_rate * max(self._latency_ewma, 1e-4)
        cap = int(min(self._max_batch_rows,
                      max(self._min_batch_rows, target)))
        batch: list = []
        taken = 0

        def pop_into(cls):
            nonlocal taken
            r = self._queues[cls].popleft()
            self._queued_rows -= len(r.rows)
            batch.append(r)
            taken += len(r.rows)

        # phase 1: reserved share per class (an oversize first request is
        # admitted whole — requests never split)
        for cls in _CLASSES:
            share = max(1, int(cap * _RESERVED[cls]))
            used = 0
            q = self._queues[cls]
            while q and taken < cap and (
                used == 0 or used + len(q[0].rows) <= share
            ):
                used += len(q[0].rows)
                pop_into(cls)
        # phase 2: leftover capacity fills oldest-first across classes
        while taken < cap:
            live = [c for c in _CLASSES if self._queues[c]]
            if not live:
                break
            cls = min(live, key=lambda c: self._queues[c][0].enqueued_at)
            if batch and taken + len(self._queues[cls][0].rows) > cap:
                break
            pop_into(cls)
        return batch, shed

    def _dispatch(self, batch: list) -> "_InFlight | None":
        """Async half: partition requests by device routing, enqueue ONE
        shape-bucketed device dispatch for the device rows (no readback),
        and hand host-routed requests to the host pool. Returns the
        in-flight device entry, or None for a host-only batch."""
        t0 = time.monotonic()
        m = _metrics()
        with self._lock:
            self._seq += 1
            seq = self._seq
        wait_t = m.timer("serving.wait_s")
        for r in batch:
            wait_t.update(t0 - r.enqueued_at)
        m.meter("serving.batches").mark()
        # occupancy histogram: requests coalesced per batch (the Timer is
        # a generic histogram; values are counts, not seconds)
        m.timer("serving.batch_occupancy").update(float(len(batch)))
        # one serving.batch span per dispatched batch: parented under the
        # FIRST sampled member's queue span (which makes a lone flow's
        # trace a clean chain) and LINKED to every sampled member — the
        # fan-in of cross-client coalescing that a parent tree alone
        # cannot express. Queue-wait spans close here: the wait is over.
        batch_span = NOOP_SPAN
        for r in batch:
            qs = r.queue_span
            if qs.sampled:
                qs.set_attr("batch_seq", seq)
                if not batch_span.sampled:
                    batch_span = tracer().start(
                        SPAN_SERVING_BATCH, qs,
                        attrs={"batch_seq": seq, "n_requests": len(batch)},
                    )
                batch_span.add_link(qs)
            qs.finish()
        dev_reqs = [r for r in batch if r.use_device]
        host_reqs = [r for r in batch if not r.use_device]
        pending = None
        dev_rows: list = []
        dev_map: list = []
        ordinal = None
        if dev_reqs:
            floor = 0
            for i, r in enumerate(dev_reqs):
                if r.min_bucket:
                    floor = max(floor, r.min_bucket)
                for j, row in enumerate(r.rows):
                    dev_rows.append(row)
                    dev_map.append((i, j))
            from corda_tpu.faultinject import check_site
            from corda_tpu.verifier.batch import dispatch_signature_rows

            bucket = self._shapes.bucket_for(len(dev_rows), floor=floor)

            def lanes_of(pending):
                # ground truth from the dispatch itself: each scheme
                # bucket pads independently, and PendingRows sums the
                # lanes the kernels REALLY ran (a shape-table estimate
                # would under-count mixed-scheme batches)
                return getattr(pending, "padded_lanes", 0) or len(dev_rows)

            try:
                # the scheduler-level fail site: a FaultPlan can force the
                # WHOLE batch onto the host reference path deterministically.
                # The batch span is ACTIVATED around the dispatch so a fault
                # injected here (or at the nested verifier.device site)
                # stamps this batch's trace id onto its chaos event —
                # without it the dispatcher thread has no ambient context.
                # stamp_span lets profiled kernels inside the dispatch tag
                # this batch's span with their kernel/bucket (no-op unless
                # the profiler is on AND the span is sampled)
                with tracer().activate(batch_span), stamp_span(batch_span):
                    check_site("serving.dispatch")
                    prof = active_profiler()
                    if prof is None:
                        pending = dispatch_signature_rows(
                            dev_rows, use_device=True, min_bucket=bucket
                        )
                    else:
                        pending = prof.profile(
                            KERNEL_SERVING_DISPATCH,
                            lambda: dispatch_signature_rows(
                                dev_rows, use_device=True, min_bucket=bucket
                            ),
                            rows=len(dev_rows), bucket=lanes_of,
                        )
                # bucket-induced waste, visible with the profiler OFF:
                # wasted lanes per dispatch (histogram) + the cumulative
                # fill-ratio gauge registered in _register_process_gauges
                padded = lanes_of(pending)
                m.timer("serving.batch_pad_waste").update(
                    float(padded - len(dev_rows))
                )
                self._real_rows += len(dev_rows)
                self._padded_rows += padded
                # per-chip attribution: single-chip dispatch runs on the
                # default ordinal (jax is up — the dispatch succeeded);
                # stamped on the span + result even before the mesh
                # scheduler lands, and fed to the per-device telemetry
                # registry when it is on
                ordinal = default_device_ordinal()
                batch_span.set_attr("device", ordinal)
                mon = active_devicemon()
                if mon is not None:
                    mon.record_dispatch(
                        ordinal, rows=len(dev_rows), padded_lanes=padded
                    )
            except Exception:
                m.counter("serving.device_failover").inc()
                batch_span.set_attr("device_failover", True)
                mon = active_devicemon()
                if mon is not None:
                    mon.record_failure(default_device_ordinal())
                host_reqs = host_reqs + dev_reqs
                dev_reqs, pending = [], None
        device_entry = bool(dev_reqs and pending is not None)
        batch_span.set_attr(
            "routing", "device" if device_entry else "host"
        )
        if host_reqs:
            # a host-only batch's span closes when the host pool settles
            # it; a mixed batch's span rides the device entry instead
            host_span = batch_span if not device_entry else NOOP_SPAN
            try:
                self._host_pool.submit(
                    self._settle_host, host_reqs, seq, host_span
                )
            except RuntimeError:
                self._settle_host(host_reqs, seq, host_span)  # pool closed
        if device_entry:
            return _InFlight(dev_reqs, pending, len(dev_rows), dev_map,
                             seq, t0, span=batch_span, device=ordinal)
        return None

    # ------------------------------------------------------------ collect
    @staticmethod
    def _settle_host(requests: list, seq: int, span=NOOP_SPAN) -> None:
        """Host reference path for host-routed (or failed-over) requests;
        runs on the host pool so a bulk host window never delays an
        unrelated batch's settlement."""
        from corda_tpu.crypto import is_valid

        slo = active_slo()
        for r in requests:
            try:
                mask = np.array(
                    [is_valid(k, s, m) for k, s, m in r.rows], dtype=bool
                )
                if slo is not None:
                    slo.observe(
                        r.priority, time.monotonic() - r.enqueued_at
                    )
                _complete(r.future, result=RowResult(mask, 0, seq))
            except Exception as e:
                if slo is not None:
                    slo.observe(
                        r.priority, time.monotonic() - r.enqueued_at,
                        error=True,
                    )
                span.set_error(e)
                _complete(r.future, error=e)
        span.finish()

    def _collect_loop(self) -> None:
        # Settle in COMPLETION order, not dispatch order: with several
        # batches in flight (possibly different shape buckets), the one
        # that lands first should resolve its futures first — blocking on
        # the oldest dispatch would stack every later batch's settlement
        # behind the slowest kernel. When nothing is ready, block on the
        # oldest (the FIFO degenerate case, identical to the old loop).
        live: list[_InFlight] = []
        draining = False
        while True:
            while not draining:
                try:
                    entry = self._inflight_q.get(block=not live)
                except _queue.Empty:
                    break
                if entry is None:
                    draining = True
                else:
                    live.append(entry)
            if not live:
                if draining:
                    return
                continue
            entry = next(
                (e for e in live if _pending_ready(e.pending)), None
            )
            if entry is None:
                entry = live[0]
            elif entry is not live[0]:
                _metrics().counter("serving.settle_reorder").inc()
            live.remove(entry)
            self._settle_entry(entry)

    def _settle_entry(self, entry: "_InFlight") -> None:
        try:
            self._settle(entry)
        except Exception as e:
            mon = active_devicemon()
            if mon is not None and entry.device is not None:
                mon.record_settle(
                    entry.device, time.monotonic() - entry.t0, ok=False
                )
            slo = active_slo()
            if slo is not None:
                now = time.monotonic()
                for r in entry.requests:
                    slo.observe(
                        r.priority, now - r.enqueued_at, error=True
                    )
            entry.span.set_error(e)
            entry.span.finish()
            for r in entry.requests:
                _complete(r.future, error=e)
        finally:
            with self._lock:
                self._inflight -= 1
                self._lock.notify_all()

    def _settle(self, entry: _InFlight) -> None:
        masks = [np.zeros(len(r.rows), dtype=bool) for r in entry.requests]
        n_device = [0] * len(entry.requests)
        dev_mask = entry.pending.collect()
        on_device = getattr(
            entry.pending, "device_mask",
            np.zeros(entry.n_rows, dtype=bool),
        )
        for k, (i, j) in enumerate(entry.dev_map):
            masks[i][j] = bool(dev_mask[k])
            if on_device[k]:
                n_device[i] += 1
        latency = time.monotonic() - entry.t0
        m = _metrics()
        m.timer("serving.batch_latency_s").update(latency)
        mon = active_devicemon()
        if mon is not None and entry.device is not None:
            # the per-device completion heartbeat + execute-wall EWMA the
            # watchdog's straggler/stall rules evaluate
            mon.record_settle(entry.device, latency)
        slo = active_slo()
        if slo is not None:
            now = time.monotonic()
            for r in entry.requests:
                # end-to-end (admission→settle) latency per priority
                # class — the windowed p99 the SLO objectives bound
                slo.observe(r.priority, now - r.enqueued_at)
        entry.span.set_attr("n_rows", entry.n_rows)
        entry.span.set_attr("device_rows", int(sum(n_device)))
        entry.span.finish()
        with self._lock:
            self._latency_ewma = (
                latency if self._latency_ewma == 0.0
                else 0.7 * self._latency_ewma + 0.3 * latency
            )
        for r, mask, nd in zip(entry.requests, masks, n_device):
            _complete(r.future, result=RowResult(
                mask, nd, entry.seq, device=entry.device,
            ))

    # ----------------------------------------------------------- lifecycle
    def shutdown(self, timeout: float = 30.0) -> None:
        """Stop accepting work; QUEUED and in-flight requests all complete
        (with verdicts — the drain processes them — or with the dispatch
        error), waiting up to ``timeout`` per stage for a wedged device
        (clients' ``FuturePending.collect`` has its own bound for that
        case). Idempotent: a second shutdown is a no-op."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._paused = False
            self._lock.notify_all()
        self._dispatcher.join(timeout=timeout)
        # dispatcher is done submitting: let the host pool finish its
        # settlements, then the collector drain the device pipeline
        self._host_pool.shutdown(wait=True)
        self._collector.join(timeout=timeout)


class FuturePending:
    """Adapter giving a scheduler Future the two-phase ``collect()``
    surface of ``PendingTxCheck`` — drop-in for the notary/wavefront
    pipelines that enqueue now and block later. ``collect`` is BOUNDED:
    a wedged device (tunneled backend stall) surfaces as a ServingError
    the caller's per-window error handling turns into failed requests,
    never an indefinitely hung notary thread. The default leaves ample
    room for a cold remote compile (~3 min on the tunnel)."""

    __slots__ = ("_future", "_timeout")

    def __init__(self, future: Future, timeout: float = 600.0):
        self._future = future
        self._timeout = timeout

    def collect(self):
        try:
            return self._future.result(timeout=self._timeout)
        except _FutTimeout:
            raise ServingError(
                f"scheduler did not settle the batch within {self._timeout}s"
            ) from None


# ------------------------------------------------- process-global instance
#
# The device dispatch queue is a per-process resource (one backend, one
# compile cache), so production callers share ONE scheduler. Lazy
# creation; a shut-down global is transparently replaced on next access
# (tests shut it down freely).

_global: DeviceScheduler | None = None
_global_lock = threading.Lock()


def device_scheduler() -> DeviceScheduler:
    global _global
    with _global_lock:
        if _global is None or _global.closed:
            _global = DeviceScheduler()
        return _global


def configure_scheduler(**kwargs) -> DeviceScheduler:
    """Replace the process-global scheduler (shutting down the old one);
    node startup calls this with config-derived bounds."""
    global _global
    with _global_lock:
        old, _global = _global, None
    if old is not None:
        old.shutdown()
    with _global_lock:
        _global = DeviceScheduler(**kwargs)
        return _global


def shutdown_scheduler() -> None:
    global _global
    with _global_lock:
        sched, _global = _global, None
    if sched is not None:
        sched.shutdown()


def _register_process_gauges() -> None:
    """The ``serving.*`` gauges read THROUGH the global accessor rather
    than binding a scheduler instance: a shut-down/replaced scheduler is
    never pinned by the metric registry, a dead one reads as empty, and
    test-constructed local schedulers cannot hijack the production
    surface."""
    m = _metrics()

    def live(read):
        def fn():
            sched = _global
            if sched is None or sched.closed:
                return 0
            try:
                return read(sched)
            except Exception:
                return 0
        return fn

    m.gauge("serving.queue_rows", live(lambda s: s._queued_rows))
    m.gauge("serving.queue_depth", live(lambda s: sum(
        len(q) for q in s._queues.values()
    )))
    m.gauge("serving.inflight", live(lambda s: s._inflight))
    # cumulative device-batch fill ratio (real rows / padded lanes): the
    # bucket-waste health read next to batch_occupancy — 1.0 before any
    # device dispatch (nothing padded means nothing wasted)
    m.gauge("serving.batch_fill_ratio", live(
        lambda s: (
            s._real_rows / s._padded_rows if s._padded_rows else 1.0
        )
    ))


_register_process_gauges()
