"""Notary services: uniqueness attestation over transactions.

Parity with the reference's notary service tier
(node/.../services/transactions/ + core/.../node/services/NotaryService.kt):

- ``SimpleNotaryService`` — non-validating: accepts a *tear-off*
  (FilteredTransaction revealing only inputs/timewindow/notary), checks the
  Merkle proofs, commits, signs (reference: SimpleNotaryService.kt:18 +
  NonValidatingNotaryFlow).
- ``ValidatingNotaryService`` — resolves and fully verifies the transaction
  (signatures minus its own + contracts) before committing (reference:
  ValidatingNotaryService.kt:11 + ValidatingNotaryFlow.kt:17-51).
- ``BatchedNotaryService`` — the TPU tier: requests accumulate into a
  window, all signatures across the batch verify as one scheme-bucketed
  device dispatch (verifier.check_transactions), inputs commit via one
  ``commit_batch`` storage round-trip, responses sign per-tx. This is the
  shape BASELINE config #5 (≥10k notarised tx/sec) measures.

Time-window checking mirrors the reference's ``TimeWindowChecker`` (30 s
tolerance around the notary clock).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

from corda_tpu.crypto import KeyPair, SecureHash, TransactionSignature, sign_tx_id
from corda_tpu.ledger import (
    ComponentGroupType,
    FilteredTransaction,
    Party,
    SignedTransaction,
    TimeWindow,
)
from corda_tpu.observability import (
    SPAN_NOTARY_ATTEST,
    SPAN_NOTARY_SUBMIT,
    tracer,
)

from .uniqueness import NotaryError, UniquenessProvider

TIME_TOLERANCE_MICROS = 30 * 1_000_000  # reference: TimeWindowChecker 30s


class NotaryInternalException(Exception):
    pass


class NotaryService:
    """Base: identity + uniqueness + signing + time-window policy.

    Idempotent resubmission: every successful attestation is remembered
    (bounded, keyed by tx id), so a client retrying after a lost response
    — leader change mid-commit, dropped reply, crash-replayed flow — gets
    the ORIGINAL signature back without re-running verification or a
    consensus round, and without its already-consumed inputs reading as a
    double-spend (the uniqueness providers are idempotent per tx id for
    the same reason; the cache is the fast path over that guarantee,
    matching the reference's re-notarisation behavior)."""

    SIGNED_CACHE_MAX = 8192

    def __init__(
        self,
        identity: Party,
        keypair: KeyPair,
        uniqueness: UniquenessProvider,
        clock=time.time,
    ):
        if keypair.public != identity.owning_key:
            raise ValueError("notary keypair must match identity key")
        self.identity = identity
        self._keypair = keypair
        self.uniqueness = uniqueness
        self._clock = clock
        self._signed_cache: dict = {}
        # tx id -> aggregate quorum certificate for the consensus round
        # that committed it (BFT/BLS clusters only; docs/BATCH_VERIFY.md).
        # Rides the signed cache's lock, ordering and eviction so a
        # retry answered from cache can return the ORIGINAL aggregate —
        # never a re-signed one — alongside the original attestation.
        self._qc_cache: dict = {}
        self._signed_order: "list" = []
        self._signed_lock = threading.Lock()
        # durable attestation journal (docs/DURABILITY.md): a provider
        # offering recovered_signatures/record_signature (the durable
        # tier) preloads the signed cache across restarts — a recovering
        # notary answers pre-crash retries with the ORIGINAL attestation
        # instead of re-running verification, and never double-attests
        self._sig_journal = getattr(uniqueness, "record_signature", None)
        recovered = getattr(uniqueness, "recovered_signatures", None)
        if recovered is not None:
            for tx_id, sig in recovered().items():
                self._signed_cache[tx_id] = sig
                self._signed_order.append(tx_id)

    def sign(self, tx_id: SecureHash) -> TransactionSignature:
        return sign_tx_id(self._keypair.private, self._keypair.public, tx_id)

    def cached_signature(self, tx_id: SecureHash) -> TransactionSignature | None:
        """The original attestation for an already-notarised tx, if still
        in the bounded cache (a miss just means the full — idempotent —
        path runs again)."""
        with self._signed_lock:
            return self._signed_cache.get(tx_id)

    def cached_qc(self, tx_id: SecureHash):
        """The aggregate quorum certificate attached to a cached
        attestation, if any — what lets a recovering BFT-clustered
        notary answer a retry with the round's original aggregate."""
        with self._signed_lock:
            return self._qc_cache.get(tx_id)

    def remember_signature(
        self, tx_id: SecureHash, sig: TransactionSignature, qc=None
    ) -> None:
        with self._signed_lock:
            if tx_id in self._signed_cache:
                if qc is not None:
                    self._qc_cache.setdefault(tx_id, qc)
                return
            self._signed_cache[tx_id] = sig
            if qc is not None:
                self._qc_cache[tx_id] = qc
            self._signed_order.append(tx_id)
            if len(self._signed_order) > self.SIGNED_CACHE_MAX:
                evict = self._signed_order[: len(self._signed_order) // 2]
                del self._signed_order[: len(self._signed_order) // 2]
                for t in evict:
                    self._signed_cache.pop(t, None)
                    self._qc_cache.pop(t, None)
        if self._sig_journal is not None:
            # outside the cache lock: the journal append takes the
            # provider's own lock and rides the next group-commit flush
            self._sig_journal(tx_id, sig)

    def check_time_window(self, tw: TimeWindow | None) -> None:
        """Reject if the notary's now (±tolerance) is outside the window
        (reference: TimeWindowChecker.isValid)."""
        if tw is None:
            return
        now = int(self._clock() * 1_000_000)
        ok = (
            tw.from_time is None or now + TIME_TOLERANCE_MICROS >= tw.from_time
        ) and (
            tw.until_time is None or now - TIME_TOLERANCE_MICROS < tw.until_time
        )
        if not ok:
            raise NotaryError(f"time window {tw} outside current time")

    def _check_notary(self, notary: Party | None, tx_id) -> None:
        if notary is None or notary.owning_key != self.identity.owning_key:
            raise NotaryError(
                f"transaction {tx_id} names a different notary than this service"
            )


class SimpleNotaryService(NotaryService):
    """Non-validating: trusts the requester about everything except
    uniqueness; sees only the tear-off (privacy property the reference's
    NonValidatingNotaryFlow provides)."""

    def process(self, ftx: FilteredTransaction, caller_name: str) -> TransactionSignature:
        trc = tracer()
        with trc.start(SPAN_NOTARY_ATTEST, trc.current(),
                       attrs={"tx.id": str(ftx.id), "service": "simple"}):
            return self._process_inner(ftx, caller_name)

    def _process_inner(self, ftx, caller_name):
        cached = self.cached_signature(ftx.id)
        if cached is not None:
            return cached  # duplicate resubmission: original attestation
        ftx.verify()  # adversarial input: every proof must chain to ftx.id
        # inputs, timewindow and notary MUST be fully visible in the
        # tear-off — a requester hiding the timewindow group would
        # otherwise bypass expiry checking entirely
        ftx.check_all_components_visible(ComponentGroupType.INPUTS)
        ftx.check_all_components_visible(ComponentGroupType.TIMEWINDOW)
        ftx.check_all_components_visible(ComponentGroupType.NOTARY)
        inputs = ftx.components_of(ComponentGroupType.INPUTS)
        tws = ftx.components_of(ComponentGroupType.TIMEWINDOW)
        notaries = ftx.components_of(ComponentGroupType.NOTARY)
        self._check_notary(notaries[0] if notaries else None, ftx.id)
        self.check_time_window(tws[0] if tws else None)
        self.uniqueness.commit(list(inputs), ftx.id, caller_name)
        sig = self.sign(ftx.id)
        self.remember_signature(ftx.id, sig)
        return sig


class ValidatingNotaryService(NotaryService):
    """Validating: full resolution + signature + contract verification
    before commit (reference: ValidatingNotaryFlow.kt:23-51)."""

    def process(
        self, stx: SignedTransaction, resolve_state, caller_name: str
    ) -> TransactionSignature:
        trc = tracer()
        with trc.start(SPAN_NOTARY_ATTEST, trc.current(),
                       attrs={"tx.id": str(stx.id), "service": "validating"}):
            return self._process_inner(stx, resolve_state, caller_name)

    def _process_inner(self, stx, resolve_state, caller_name):
        cached = self.cached_signature(stx.id)
        if cached is not None:
            return cached  # duplicate resubmission: original attestation
        stx.verify_signatures_except({self.identity.owning_key})
        wtx = stx.tx
        self._check_notary(wtx.notary, stx.id)
        ltx = wtx.to_ledger_transaction(resolve_state)
        ltx.verify()
        self.check_time_window(wtx.time_window)
        self.uniqueness.commit(list(wtx.inputs), stx.id, caller_name)
        sig = self.sign(stx.id)
        self.remember_signature(stx.id, sig)
        return sig


class _PendingRequest:
    __slots__ = ("stx", "resolve_state", "caller", "future", "span",
                 "deadline_t")

    def __init__(self, stx, resolve_state, caller, span=None):
        self.stx = stx
        self.resolve_state = resolve_state
        self.caller = caller
        self.future: Future = Future()
        # notary.submit span (request → response), captured on the
        # CALLER's thread — the flusher pipeline threads that settle the
        # future have no ambient trace context of their own
        self.span = span
        # propagated end-to-end deadline (absolute epoch, or None),
        # captured on the caller's thread like the span: the flush window
        # drops requests whose flow is already dead (docs/OVERLOAD.md)
        self.deadline_t: float | None = None


class BatchedNotaryService(NotaryService):
    """The TPU-batched validating notary.

    ``request()`` returns a Future[TransactionSignature]; requests flush as
    one batch when ``max_batch`` accumulate or ``window_s`` elapses since
    the first pending request. A flush:

    1. verifies ALL signatures of the batch in one bucketed device dispatch
       (``verifier.check_transactions`` — the per-signature JCA loop of the
       reference collapsed into vmapped kernels);
    2. runs contract/constraint verification per-tx on host;
    3. settles uniqueness via one ``commit_batch`` round-trip;
    4. signs every accepted tx id.

    ``process_batch`` is the synchronous core, callable directly (the
    loadtest harness and bench drive it without the window thread).
    """

    def __init__(
        self, identity, keypair, uniqueness, *,
        max_batch: int = 1024, window_s: float = 0.005,
        use_device: bool = True, validating: bool = True,
        use_scheduler: bool = True,
        metrics=None, clock=time.time,
    ):
        super().__init__(identity, keypair, uniqueness, clock)
        self._max_batch = max_batch
        self._window_s = window_s
        self._use_device = use_device
        self._use_scheduler = use_scheduler
        self._validating = validating
        self._metrics = metrics
        self._pending: list[_PendingRequest] = []
        self._lock = threading.Lock()
        self._flusher: threading.Thread | None = None
        self._wake = threading.Event()
        self._stopped = False
        if use_device:
            # warm the link-RTT probe (and its tiny jit) off the hot path:
            # the first window's break-even gate otherwise pays the probe
            # compile + round trips inside request latency (the r4 trader
            # artifact lost ~10% of its timed region to exactly this)
            threading.Thread(
                target=self._warm_probe, daemon=True, name="notary-probe-warm"
            ).start()

    @staticmethod
    def _warm_probe() -> None:
        try:
            from corda_tpu.ops.txid import _measured_link_rtt_s, ids_tier

            _measured_link_rtt_s()
            ids_tier()
        except Exception:
            pass  # no backend: gates fall back to host anyway

    # ---------------------------------------------------------- sync core

    def dispatch_ids(self, requests):
        """Enqueue the batch's device Merkle-id sweep — receive-path
        integrity: every tx's id is recomputed from its component bytes
        (reference gets this implicitly from WireTransaction.kt:139-195 —
        the id IS the content hash); the signature batch then checks each
        signer actually signed that recomputed root. Returns a pending
        whose ``collect()`` primes the id caches (None on host tiers)."""
        if not self._use_device:
            return None
        from corda_tpu.ops.txid import dispatch_prime_ids

        return dispatch_prime_ids([r[0] for r in requests])

    def dispatch_batch(self, requests, pending_ids=None, pipelined=True,
                       trace=None):
        """Enqueue the device half (signature ladders) of a batch; the
        returned pending check settles in ``settle_batch``. Splitting the
        two is what hides the interconnect round trip: while batch k's
        ladders run on device, the host validates/commits/signs batch k-1
        (see ``process_stream``). ``pending_ids`` is an already-enqueued
        id sweep (its round trip overlapped with earlier batches);
        without one the sweep runs inline.

        ``pipelined=False`` marks a SOLO window — nothing else in flight
        to hide the link round trip behind — and routes it through the
        one-shot break-even gate (ops.txid): a lightly-loaded service's
        handful-of-tx window verifies faster on host than it can round-
        trip a tunneled chip (the r4 trader demo ran 0.4× host before
        this). Pipelined windows — the throughput shape — always take
        the device: their round trips overlap neighbouring windows'
        host work, which is exactly the assumption the break-even
        formula does NOT hold under."""
        from corda_tpu.verifier import dispatch_transactions

        if pending_ids is None:
            pending_ids = self.dispatch_ids(requests)
        if pending_ids is not None:
            pending_ids.collect()
        use_device = self._use_device
        if use_device and not pipelined:
            from corda_tpu.ops.txid import device_verify_worthwhile

            n_rows = sum(len(r[0].sigs) for r in requests)
            use_device = device_verify_worthwhile(n_rows)
        if self._use_scheduler:
            # route the window through the process-global serving
            # scheduler (BULK class): its continuous-batching loop
            # coalesces this window with concurrent verifier/flow traffic
            # and keeps up to its pipeline depth in flight — the same
            # round-trip overlap process_stream arranged privately. The
            # routing verdict (device vs host after the break-even gate)
            # travels with the request; host windows coalesce too.
            from corda_tpu.serving import (
                BULK,
                FuturePending,
                ServingError,
                device_scheduler,
            )

            try:
                return FuturePending(device_scheduler().submit_transactions(
                    [r[0] for r in requests],
                    [{self.identity.owning_key}] * len(requests),
                    priority=BULK, use_device=use_device,
                    min_bucket=self._max_batch if use_device else None,
                    # explicit propagation: the flusher thread dispatching
                    # this window is not the traced caller's thread
                    trace=(
                        trace if trace is not None else tracer().current()
                    ),
                ))
            except ServingError:
                pass  # saturated/closed: degrade to the direct dispatch
        return dispatch_transactions(
            [r[0] for r in requests],
            [{self.identity.owning_key}] * len(requests),
            use_device=use_device,
            # one compiled kernel shape across ragged window flushes
            min_bucket=self._max_batch if use_device else None,
        )

    def process_batch(
        self, requests: list[tuple[SignedTransaction, object, str]]
    ) -> list[TransactionSignature | Exception]:
        """Verify + commit + sign a batch; one result slot per request."""
        return self.settle_batch(requests, self.dispatch_batch(requests))

    def process_stream(
        self, batches, *, depth: int | None = None
    ) -> list[list[TransactionSignature | Exception]]:
        """Pipelined notarisation over an iterable of request batches.

        Keeps up to ``depth`` batches' signature checks in flight on the
        device while the host settles (validates + commits + signs) earlier
        batches — the steady-state shape of the ≥10k-tx/sec target, where
        per-batch device latency (dominated by the tunneled link's ~100 ms
        round trip) must overlap host work rather than serialize with it.
        ``depth=None`` self-sizes: 3 on a single chip, widening to the
        serving scheduler's mesh stripe width when windows route through
        a striped scheduler — 3 in-flight windows feed at most 3 of 8
        chips, so a mesh pipeline must carry at least one window per
        stripe member to saturate it.

        The uniqueness commit is its own pipeline stage: for a CLUSTERED
        notary (Raft/BFT) ``commit_batch_async`` puts window N's consensus
        round in flight while window N+1's signatures run on device and
        window N−1's response signing streams back — without this the
        replication round serialized the whole pipeline (r4: 4.7k tx/s
        clustered vs 10.6k single-service; reference comparison:
        RaftUniquenessProvider.kt:4-17 blocks per commit, inherited by
        every notary flow).
        """
        from collections import deque

        if depth is None:
            depth = 3
            if self._use_scheduler and self._use_device:
                from corda_tpu.serving import device_scheduler

                try:
                    depth = max(depth, device_scheduler().mesh_stripe_width())
                except Exception:
                    pass  # scheduler unavailable: single-chip default
        priming: deque = deque()     # (batch, pending id sweep)
        verifying: deque = deque()   # (batch, pending sig-check)
        committing: deque = deque()  # (batch, staged validate+commit)
        signing: deque = deque()     # (results, live idxs, pending sigs)
        out: list = []

        def advance(drain: bool = False):
            if len(priming) >= (1 if drain else depth):
                b, ids = priming.popleft()
                verifying.append((b, self.dispatch_batch(b, ids)))
            if len(verifying) >= (1 if drain else depth):
                b, pending = verifying.popleft()
                committing.append((b, self.settle_validate(b, pending)))
            if len(committing) >= (1 if drain else depth):
                b, staged = committing.popleft()
                signing.append(self.settle_sign(b, *staged))
            if len(signing) >= (1 if drain else depth):
                out.append(self.finalize_batch(*signing.popleft()))

        for batch in batches:
            # stage 0: enqueue the id sweep — its readback happens a
            # depth later, overlapped with other batches' device time
            priming.append((batch, self.dispatch_ids(batch)))
            advance()
        while priming or verifying or committing or signing:
            advance(drain=True)
        return out

    def settle_batch(
        self, requests, pending
    ) -> list[TransactionSignature | Exception]:
        """Blocking half: collect the signature masks, then validate,
        commit and sign."""
        return self.finalize_batch(*self.settle_commit(requests, pending))

    def settle_commit(self, requests, pending):
        """Collect the signature masks, validate, commit, and ENQUEUE the
        response signing; ``finalize_batch`` fills in the signatures."""
        return self.settle_sign(requests, *self.settle_validate(requests, pending))

    def settle_validate(self, requests, pending):
        """Collect the signature masks, validate, and ENQUEUE the
        uniqueness commit (async for consensus providers — the round
        replicates while later windows verify on device). Returns the
        staged tuple ``settle_sign`` consumes."""
        n = len(requests)
        results: list = [None] * n
        report = pending.collect()
        live: list[int] = []
        for i, err in enumerate(report.results):
            if err is not None:
                results[i] = NotaryError(f"signature check failed: {err}")
            else:
                live.append(i)
        if self._validating:
            from corda_tpu.ledger.ledger_tx import verify_ledger_batch

            resolved: list[int] = []
            ltxs = []
            for i in live:
                stx, resolve_state, _caller = requests[i]
                try:
                    self._check_notary(stx.tx.notary, stx.id)
                    self.check_time_window(stx.tx.time_window)
                    ltxs.append(stx.tx.to_ledger_transaction(resolve_state))
                    resolved.append(i)
                except Exception as e:
                    results[i] = NotaryError(f"validation failed: {e}")
            # contract semantics dispatch once per contract class across
            # the batch (verify_batch fast paths) instead of per tx
            errs = verify_ledger_batch(ltxs)
            still_live = []
            for i, err in zip(resolved, errs):
                if err is None:
                    still_live.append(i)
                else:
                    results[i] = NotaryError(f"validation failed: {err}")
            live = still_live
        else:
            still_live = []
            for i in live:
                stx = requests[i][0]
                try:
                    self._check_notary(stx.tx.notary, stx.id)
                    self.check_time_window(stx.tx.time_window)
                    still_live.append(i)
                except Exception as e:
                    results[i] = e
            live = still_live
        commit_reqs = [
            (list(requests[i][0].tx.inputs), requests[i][0].id, requests[i][2])
            for i in live
        ]
        pending_commit = self.uniqueness.commit_batch_async(commit_reqs)
        return results, live, pending_commit, report.n_device > 0

    def settle_sign(self, requests, results, live, pending_commit, on_device):
        """Resolve the (possibly in-flight) uniqueness commit and enqueue
        response signing; ``finalize_batch`` fills in the signatures."""
        conflicts = pending_commit.collect()
        qc = self._collect_qc()
        accepted: list[int] = []
        for i, conflict in zip(live, conflicts):
            if conflict is not None:
                results[i] = NotaryError(
                    f"input states of {requests[i][0].id} already consumed",
                    conflict,
                )
            else:
                accepted.append(i)
        # response signing follows the window's VERIFY routing: a window
        # whose signature check ran on host (solo/below break-even, or a
        # host-only tier) signs on host too — one coherent decision per
        # window rather than a second gate with different constants
        accepted_ids = [requests[i][0].id for i in accepted]
        pending_sigs = self._dispatch_sign(accepted_ids, on_device=on_device)
        return results, accepted, pending_sigs, accepted_ids, qc

    def _collect_qc(self):
        """Fetch (and independently verify) the quorum certificate of the
        round just collected — only a BFT uniqueness provider with BLS
        membership offers one. Verification is ONE aggregate pairing
        check per consensus round, not per transaction; a certificate
        that fails it is dropped (the round's ed25519 attestations
        already carry correctness)."""
        take = getattr(self.uniqueness, "take_qc", None)
        qc = take() if take is not None else None
        if qc is None:
            return None
        keys = getattr(self.uniqueness, "bls_member_keys", None) or []
        if not qc.verify(keys):
            if self._metrics is not None:
                self._metrics.counter("notary.qc.rejected").inc()
            return None
        if self._metrics is not None:
            self._metrics.counter("notary.qc.cached").inc()
        return qc

    def finalize_batch(
        self, results, accepted, pending_sigs, accepted_ids=None, qc=None
    ) -> list[TransactionSignature | Exception]:
        """Fill in the (possibly device-batched) response signatures."""
        for slot, (i, sig) in enumerate(zip(accepted, pending_sigs.collect())):
            results[i] = sig
            if accepted_ids is not None:
                # remember attestations so duplicate resubmissions (client
                # retry after a lost response) return the original success
                self.remember_signature(accepted_ids[slot], sig, qc=qc)
        if self._metrics is not None:
            self._metrics.meter("notary.requests").mark(len(results))
            self._metrics.meter("notary.committed").mark(
                sum(1 for r in results if isinstance(r, TransactionSignature))
            )
        return results

    def _dispatch_sign(self, tx_ids: list[SecureHash], on_device: bool = True):
        """Enqueue response signing: one device comb-kernel batch when the
        notary key is ed25519 (the default scheme) and the window's verify
        half ran on device (``on_device`` — see settle_commit), host loop
        otherwise. Signatures are RFC 8032 deterministic either way, so
        device and host paths emit identical bytes."""
        from corda_tpu.crypto.schemes import EDDSA_ED25519_SHA512

        if (
            self._use_device
            and on_device
            and tx_ids
            and self._keypair.private.scheme_id == EDDSA_ED25519_SHA512
        ):
            from corda_tpu.crypto.signatures import (
                CURRENT_PLATFORM_VERSION,
                SignableData,
                SignatureMetadata,
            )
            from corda_tpu.ops.ed25519_sign import ed25519_sign_dispatch

            meta = SignatureMetadata(
                CURRENT_PLATFORM_VERSION, EDDSA_ED25519_SHA512
            )
            payloads = [SignableData(t, meta).to_bytes() for t in tx_ids]
            seed = self._keypair.private.encoded
            inner = ed25519_sign_dispatch(
                [seed] * len(tx_ids), payloads, min_bucket=self._max_batch
            )
            public = self._keypair.public

            class _DeviceSigs:
                def collect(_self):
                    return [
                        TransactionSignature(raw, public, meta)
                        for raw in inner.collect()
                    ]

            return _DeviceSigs()

        sigs = [self.sign(t) for t in tx_ids]

        class _HostSigs:
            def collect(_self):
                return sigs

        return _HostSigs()

    # ---------------------------------------------------------- async path

    def request(self, stx: SignedTransaction, resolve_state, caller: str) -> Future:
        cached = self.cached_signature(stx.id)
        if cached is not None:
            # duplicate resubmission: answer with the original attestation
            # without burning a batch slot or a consensus round
            fut: Future = Future()
            fut.set_result(cached)
            return fut
        from corda_tpu.flows.overload import active_overload, remaining_deadline

        rem = remaining_deadline()
        if rem is not None and rem <= 0.0:
            # the submitting flow's end-to-end deadline already passed:
            # shed at the door before the request burns a batch slot, a
            # device dispatch, and a consensus round (docs/OVERLOAD.md)
            ov = active_overload()
            if ov is not None:
                ov.note_deadline_shed()
            raise NotaryInternalException(
                "notary request shed: flow deadline exceeded"
            )
        trc = tracer()
        span = trc.start(SPAN_NOTARY_SUBMIT, trc.current(),
                         attrs={"tx.id": str(stx.id), "caller": caller})
        req = _PendingRequest(stx, resolve_state, caller, span=span)
        if rem is not None:
            req.deadline_t = time.time() + rem
        if span.sampled:
            def close_span(f: Future):
                err = f.exception() if not f.cancelled() else None
                if err is not None:
                    span.set_error(err)
                span.finish()

            req.future.add_done_callback(close_span)
        with self._lock:
            if self._stopped:
                # the future never settles on this path, so the span's
                # done-callback close never fires — close it here
                err = NotaryInternalException("notary service stopped")
                span.set_error(err)
                span.finish()
                raise err
            self._pending.append(req)
            if self._flusher is None:
                self._flusher = threading.Thread(
                    target=self._flush_loop, daemon=True, name="notary-batcher"
                )
                self._flusher.start()
            if len(self._pending) >= self._max_batch:
                self._wake.set()
        return req.future

    def _flush_loop(self) -> None:
        """Stage 1 of the async pipeline: window/size-batch the pending
        requests and enqueue their device signature checks. Stages 2
        (validate+commit+enqueue signing) and 3 (collect signatures,
        resolve futures) run on their own threads so consecutive windows
        overlap the device round trips instead of serializing on them —
        the same pipeline shape as ``process_stream``, driven by arrival."""
        import queue as _queue

        commit_q: _queue.Queue = _queue.Queue(maxsize=4)
        sign_q: _queue.Queue = _queue.Queue(maxsize=4)
        final_q: _queue.Queue = _queue.Queue(maxsize=4)

        def commit_loop():
            # stage 2a: collect masks + validate + ENQUEUE the uniqueness
            # commit; for consensus providers the replication round rides
            # in sign_q while this thread validates the next window
            while True:
                item = commit_q.get()
                if item is None:
                    sign_q.put(None)
                    return
                batch, pending = item
                try:
                    reqs = [(r.stx, r.resolve_state, r.caller) for r in batch]
                    staged = self.settle_validate(reqs, pending)
                    sign_q.put((batch, reqs, staged, None))
                except Exception as e:
                    sign_q.put((batch, None, None, e))

        def sign_loop():
            # stage 2b: resolve the commit, enqueue response signing
            while True:
                item = sign_q.get()
                if item is None:
                    final_q.put(None)
                    return
                batch, reqs, staged, err = item
                if err is not None:
                    final_q.put((batch, None, err))
                    continue
                try:
                    final_q.put((batch, self.settle_sign(reqs, *staged), None))
                except Exception as e:
                    final_q.put((batch, None, e))

        def finalize_loop():
            while True:
                item = final_q.get()
                if item is None:
                    return
                batch, staged, err = item
                if err is not None:
                    results: list = [err] * len(batch)
                else:
                    try:
                        results = self.finalize_batch(*staged)
                    except Exception as e:
                        results = [e] * len(batch)
                for req, res in zip(batch, results):
                    try:
                        if isinstance(res, Exception):
                            req.future.set_exception(res)
                        else:
                            req.future.set_result(res)
                    except Exception:
                        pass  # caller cancelled

        committer = threading.Thread(
            target=commit_loop, daemon=True, name="notary-committer"
        )
        signer = threading.Thread(
            target=sign_loop, daemon=True, name="notary-signer"
        )
        finalizer = threading.Thread(
            target=finalize_loop, daemon=True, name="notary-finalizer"
        )
        committer.start()
        signer.start()
        finalizer.start()
        def take_window():
            # cap every flush at max_batch: an uncapped drain under burst
            # load would exceed the pinned kernel bucket and stall this
            # thread behind a fresh compile
            with self._lock:
                batch = self._pending[: self._max_batch]
                self._pending = self._pending[self._max_batch :]
            # propagated-deadline shed (docs/OVERLOAD.md): requests whose
            # flow died while queued in the window are failed here rather
            # than carried through verify/commit/sign — under overload
            # the window is exactly where dead work piles up
            now = time.time()
            dead = [r for r in batch
                    if r.deadline_t is not None and now >= r.deadline_t]
            if dead:
                from corda_tpu.flows.overload import active_overload

                ov = active_overload()
                batch = [r for r in batch if r not in dead]
                for r in dead:
                    if ov is not None:
                        ov.note_deadline_shed()
                    try:
                        r.future.set_exception(NotaryInternalException(
                            "notary request shed: flow deadline exceeded "
                            "while batched"
                        ))
                    except Exception:
                        pass  # caller cancelled
            return batch, self._stopped

        try:
            while True:
                self._wake.wait(timeout=self._window_s)
                self._wake.clear()
                # one-window-ahead id overlap: enqueue window k+1's id
                # sweep BEFORE window k's (blocking) sweep collect inside
                # dispatch_batch, so the interconnect round trip of each
                # sweep runs under the previous window's dispatch
                ahead = None  # (batch, requests, pending id sweep)
                while True:
                    batch, stopped = take_window()
                    if batch:
                        reqs = [
                            (r.stx, r.resolve_state, r.caller) for r in batch
                        ]
                        try:
                            nxt = (batch, reqs, self.dispatch_ids(reqs))
                        except Exception as e:
                            for req in batch:
                                try:
                                    req.future.set_exception(e)
                                except Exception:
                                    pass
                            nxt = None
                    else:
                        nxt = None
                    if ahead is not None:
                        a_batch, a_reqs, a_ids = ahead
                        # first traced request parents the window's
                        # scheduler spans (members link via the batch span)
                        a_trace = next(
                            (r.span for r in a_batch
                             if r.span is not None and r.span.sampled),
                            None,
                        )
                        try:
                            # sustained load is what fills windows: a
                            # half-full-or-better window rides the device
                            # unconditionally (its round trip overlaps
                            # the neighbouring windows'), while light
                            # windows — interactive ensembles — take the
                            # one-shot break-even gate (a burst of tiny
                            # windows must not serialize on per-window
                            # device round trips)
                            commit_q.put((a_batch, self.dispatch_batch(
                                a_reqs, a_ids,
                                pipelined=(
                                    len(a_batch) >= self._max_batch // 2
                                ),
                                trace=a_trace,
                            )))
                        except Exception as e:
                            for req in a_batch:
                                try:
                                    req.future.set_exception(e)
                                except Exception:
                                    pass
                    ahead = nxt
                    if ahead is None:
                        break
                if stopped:
                    return
        finally:
            commit_q.put(None)
            committer.join(timeout=5)
            signer.join(timeout=5)
            finalizer.join(timeout=5)

    def shutdown(self) -> None:
        with self._lock:
            self._stopped = True
        self._wake.set()
        if self._flusher is not None:
            self._flusher.join(timeout=15)
