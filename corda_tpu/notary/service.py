"""Notary services: uniqueness attestation over transactions.

Parity with the reference's notary service tier
(node/.../services/transactions/ + core/.../node/services/NotaryService.kt):

- ``SimpleNotaryService`` — non-validating: accepts a *tear-off*
  (FilteredTransaction revealing only inputs/timewindow/notary), checks the
  Merkle proofs, commits, signs (reference: SimpleNotaryService.kt:18 +
  NonValidatingNotaryFlow).
- ``ValidatingNotaryService`` — resolves and fully verifies the transaction
  (signatures minus its own + contracts) before committing (reference:
  ValidatingNotaryService.kt:11 + ValidatingNotaryFlow.kt:17-51).
- ``BatchedNotaryService`` — the TPU tier: requests accumulate into a
  window, all signatures across the batch verify as one scheme-bucketed
  device dispatch (verifier.check_transactions), inputs commit via one
  ``commit_batch`` storage round-trip, responses sign per-tx. This is the
  shape BASELINE config #5 (≥10k notarised tx/sec) measures.

Time-window checking mirrors the reference's ``TimeWindowChecker`` (30 s
tolerance around the notary clock).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

from corda_tpu.crypto import KeyPair, SecureHash, TransactionSignature, sign_tx_id
from corda_tpu.ledger import (
    ComponentGroupType,
    FilteredTransaction,
    Party,
    SignedTransaction,
    TimeWindow,
)

from .uniqueness import NotaryError, UniquenessProvider

TIME_TOLERANCE_MICROS = 30 * 1_000_000  # reference: TimeWindowChecker 30s


class NotaryInternalException(Exception):
    pass


class NotaryService:
    """Base: identity + uniqueness + signing + time-window policy."""

    def __init__(
        self,
        identity: Party,
        keypair: KeyPair,
        uniqueness: UniquenessProvider,
        clock=time.time,
    ):
        if keypair.public != identity.owning_key:
            raise ValueError("notary keypair must match identity key")
        self.identity = identity
        self._keypair = keypair
        self.uniqueness = uniqueness
        self._clock = clock

    def sign(self, tx_id: SecureHash) -> TransactionSignature:
        return sign_tx_id(self._keypair.private, self._keypair.public, tx_id)

    def check_time_window(self, tw: TimeWindow | None) -> None:
        """Reject if the notary's now (±tolerance) is outside the window
        (reference: TimeWindowChecker.isValid)."""
        if tw is None:
            return
        now = int(self._clock() * 1_000_000)
        ok = (
            tw.from_time is None or now + TIME_TOLERANCE_MICROS >= tw.from_time
        ) and (
            tw.until_time is None or now - TIME_TOLERANCE_MICROS < tw.until_time
        )
        if not ok:
            raise NotaryError(f"time window {tw} outside current time")

    def _check_notary(self, notary: Party | None, tx_id) -> None:
        if notary is None or notary.owning_key != self.identity.owning_key:
            raise NotaryError(
                f"transaction {tx_id} names a different notary than this service"
            )


class SimpleNotaryService(NotaryService):
    """Non-validating: trusts the requester about everything except
    uniqueness; sees only the tear-off (privacy property the reference's
    NonValidatingNotaryFlow provides)."""

    def process(self, ftx: FilteredTransaction, caller_name: str) -> TransactionSignature:
        ftx.verify()  # adversarial input: every proof must chain to ftx.id
        # inputs, timewindow and notary MUST be fully visible in the
        # tear-off — a requester hiding the timewindow group would
        # otherwise bypass expiry checking entirely
        ftx.check_all_components_visible(ComponentGroupType.INPUTS)
        ftx.check_all_components_visible(ComponentGroupType.TIMEWINDOW)
        ftx.check_all_components_visible(ComponentGroupType.NOTARY)
        inputs = ftx.components_of(ComponentGroupType.INPUTS)
        tws = ftx.components_of(ComponentGroupType.TIMEWINDOW)
        notaries = ftx.components_of(ComponentGroupType.NOTARY)
        self._check_notary(notaries[0] if notaries else None, ftx.id)
        self.check_time_window(tws[0] if tws else None)
        self.uniqueness.commit(list(inputs), ftx.id, caller_name)
        return self.sign(ftx.id)


class ValidatingNotaryService(NotaryService):
    """Validating: full resolution + signature + contract verification
    before commit (reference: ValidatingNotaryFlow.kt:23-51)."""

    def process(
        self, stx: SignedTransaction, resolve_state, caller_name: str
    ) -> TransactionSignature:
        stx.verify_signatures_except({self.identity.owning_key})
        wtx = stx.tx
        self._check_notary(wtx.notary, stx.id)
        ltx = wtx.to_ledger_transaction(resolve_state)
        ltx.verify()
        self.check_time_window(wtx.time_window)
        self.uniqueness.commit(list(wtx.inputs), stx.id, caller_name)
        return self.sign(stx.id)


class _PendingRequest:
    __slots__ = ("stx", "resolve_state", "caller", "future")

    def __init__(self, stx, resolve_state, caller):
        self.stx = stx
        self.resolve_state = resolve_state
        self.caller = caller
        self.future: Future = Future()


class BatchedNotaryService(NotaryService):
    """The TPU-batched validating notary.

    ``request()`` returns a Future[TransactionSignature]; requests flush as
    one batch when ``max_batch`` accumulate or ``window_s`` elapses since
    the first pending request. A flush:

    1. verifies ALL signatures of the batch in one bucketed device dispatch
       (``verifier.check_transactions`` — the per-signature JCA loop of the
       reference collapsed into vmapped kernels);
    2. runs contract/constraint verification per-tx on host;
    3. settles uniqueness via one ``commit_batch`` round-trip;
    4. signs every accepted tx id.

    ``process_batch`` is the synchronous core, callable directly (the
    loadtest harness and bench drive it without the window thread).
    """

    def __init__(
        self, identity, keypair, uniqueness, *,
        max_batch: int = 1024, window_s: float = 0.005,
        use_device: bool = True, validating: bool = True,
        metrics=None, clock=time.time,
    ):
        super().__init__(identity, keypair, uniqueness, clock)
        self._max_batch = max_batch
        self._window_s = window_s
        self._use_device = use_device
        self._validating = validating
        self._metrics = metrics
        self._pending: list[_PendingRequest] = []
        self._lock = threading.Lock()
        self._flusher: threading.Thread | None = None
        self._wake = threading.Event()
        self._stopped = False

    # ---------------------------------------------------------- sync core

    def process_batch(
        self, requests: list[tuple[SignedTransaction, object, str]]
    ) -> list[TransactionSignature | Exception]:
        """Verify + commit + sign a batch; one result slot per request."""
        from corda_tpu.verifier import check_transactions

        n = len(requests)
        results: list = [None] * n
        stxs = [r[0] for r in requests]
        report = check_transactions(
            stxs,
            [{self.identity.owning_key}] * n,
            use_device=self._use_device,
        )
        live: list[int] = []
        for i, err in enumerate(report.results):
            if err is not None:
                results[i] = NotaryError(f"signature check failed: {err}")
            else:
                live.append(i)
        if self._validating:
            still_live = []
            for i in live:
                stx, resolve_state, _caller = requests[i]
                try:
                    self._check_notary(stx.tx.notary, stx.id)
                    ltx = stx.tx.to_ledger_transaction(resolve_state)
                    ltx.verify()
                    self.check_time_window(stx.tx.time_window)
                    still_live.append(i)
                except Exception as e:
                    results[i] = NotaryError(f"validation failed: {e}")
            live = still_live
        else:
            still_live = []
            for i in live:
                stx = requests[i][0]
                try:
                    self._check_notary(stx.tx.notary, stx.id)
                    self.check_time_window(stx.tx.time_window)
                    still_live.append(i)
                except Exception as e:
                    results[i] = e
            live = still_live
        commit_reqs = [
            (list(requests[i][0].tx.inputs), requests[i][0].id, requests[i][2])
            for i in live
        ]
        conflicts = self.uniqueness.commit_batch(commit_reqs)
        for i, conflict in zip(live, conflicts):
            if conflict is not None:
                results[i] = NotaryError(
                    f"input states of {requests[i][0].id} already consumed",
                    conflict,
                )
            else:
                results[i] = self.sign(requests[i][0].id)
        if self._metrics is not None:
            self._metrics.meter("notary.requests").mark(n)
            self._metrics.meter("notary.committed").mark(
                sum(1 for r in results if isinstance(r, TransactionSignature))
            )
        return results

    # ---------------------------------------------------------- async path

    def request(self, stx: SignedTransaction, resolve_state, caller: str) -> Future:
        req = _PendingRequest(stx, resolve_state, caller)
        with self._lock:
            if self._stopped:
                raise NotaryInternalException("notary service stopped")
            self._pending.append(req)
            if self._flusher is None:
                self._flusher = threading.Thread(
                    target=self._flush_loop, daemon=True, name="notary-batcher"
                )
                self._flusher.start()
            if len(self._pending) >= self._max_batch:
                self._wake.set()
        return req.future

    def _flush_loop(self) -> None:
        while True:
            self._wake.wait(timeout=self._window_s)
            self._wake.clear()
            with self._lock:
                batch, self._pending = self._pending, []
                stopped = self._stopped
            if batch:
                try:
                    results = self.process_batch(
                        [(r.stx, r.resolve_state, r.caller) for r in batch]
                    )
                except Exception as e:  # batch-level failure fails every req
                    results = [e] * len(batch)
                for req, res in zip(batch, results):
                    try:
                        if isinstance(res, Exception):
                            req.future.set_exception(res)
                        else:
                            req.future.set_result(res)
                    except Exception:
                        pass  # caller cancelled
            if stopped:
                return

    def shutdown(self) -> None:
        with self._lock:
            self._stopped = True
        self._wake.set()
        if self._flusher is not None:
            self._flusher.join(timeout=5)
