"""BFT-replicated uniqueness (Byzantine fault-tolerant notary cluster).

Role parity with the reference's BFT-SMaRt tier
(node/.../services/transactions/BFTSMaRt.kt:55+ — ``Client`` does
total-order submission and gathers signed replica replies;
``BFTNonValidatingNotaryService.Replica.executeCommand`` verifies and
commits, replying with a per-replica signature over the outcome; the client
accepts on a cluster signature quorum). The consensus engine the reference
outsources to the BFT-SMaRt jar is implemented here as PBFT-style
three-phase total-order broadcast (pre-prepare / prepare / commit with
2f+1 quorums over n = 3f+1 replicas) on this framework's messaging layer.

View changes (liveness under primary failure — BFT-SMaRt's leader-change
regency protocol): primary of view v is ``names[v % n]``. A replica whose
pending requests stall past the suspicion timeout broadcasts a SIGNED
VIEW-CHANGE carrying its prepared certificates; replicas join a view
change once f+1 peers demand one (so a single faulty replica cannot force
view churn); the new primary installs the view with a NEW-VIEW containing
2f+1 signed view-change messages and re-proposes every prepared entry —
by quorum intersection any entry committed at an honest replica appears in
at least one certificate of any 2f+1 set, so committed state is never
lost. Unordered pending requests are re-proposed by the new primary (the
client broadcasts every request to all replicas), restoring liveness.

Trust model note: phase messages ride authenticated channels (the
transport identifies senders); VIEW-CHANGE/NEW-VIEW are additionally
signed with the replica keys, so a new-view certificate is
non-repudiable. Prepare certificates inside a view-change are the
collector's claim (MAC-PBFT posture) — sufficient for crash faults and
for Byzantine replicas that cannot forge channel identities.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError

from corda_tpu.crypto import (
    KeyPair,
    PublicKey,
    sign as host_sign,
    is_valid as host_verify,
)
from corda_tpu.messaging import auto_ack
from corda_tpu.serialization import deserialize, serialize

from .uniqueness import (
    InMemoryUniquenessProvider,
    NotaryError,
    UniquenessProvider,
)

T_REQUEST = "bft.request"
T_PREPREPARE = "bft.preprepare"
T_PREPARE = "bft.prepare"
T_COMMIT = "bft.commit"
T_REPLY = "bft.reply"
T_VIEWCHANGE = "bft.viewchange"
T_NEWVIEW = "bft.newview"

_NULL_DIGEST = b""  # gap-filling no-op slot installed by a new view


def _digest(command: bytes) -> bytes:
    import hashlib

    return hashlib.sha256(command).digest()


class BFTReplica:
    """One PBFT replica executing a deterministic uniqueness state machine.

    ``names`` fixes the cluster membership; the view rotates the primary
    over it. f = (n - 1) // 3 replicas may be faulty.
    """

    def __init__(self, name: str, names: list[str], messaging, keypair: KeyPair,
                 base: UniquenessProvider | None = None,
                 replica_keys: dict[str, PublicKey] | None = None,
                 view_timeout_s: float = 1.0,
                 bls_keypair: tuple[bytes, bytes] | None = None):
        self.name = name
        self.names = list(names)
        self.n = len(names)
        self.f = (self.n - 1) // 3
        self._messaging = messaging
        self._keypair = keypair
        # optional (public 48B, private 32B) BLS share key: when present,
        # every reply additionally carries a BLS12-381 signature share
        # over the outcome so the client can settle the round with ONE
        # aggregate quorum certificate (docs/BATCH_VERIFY.md). The
        # ed25519 reply signature stays — it is the per-reply
        # authenticity check and the QC-less fallback.
        self._bls_keypair = bls_keypair
        self._replica_keys = dict(replica_keys or {})
        self.base = base or InMemoryUniquenessProvider()
        self._lock = threading.RLock()
        self.view = 0
        self._seq = 0                     # primary: next sequence number
        self._commands: dict[bytes, bytes] = {}   # digest -> command
        self._client_of: dict[bytes, str] = {}    # digest -> requesting client
        # (view, seq) -> digest accepted from that view's primary
        self._preprepared: dict[tuple[int, int], bytes] = {}
        # quorum tallies keyed by (view, seq, digest): votes for different
        # commands at one sequence — or from different views — must never
        # be conflated, or an equivocating primary could split honest
        # replicas onto divergent uniqueness maps
        self._prepares: dict[tuple[int, int, bytes], set[str]] = defaultdict(set)
        self._commits: dict[tuple[int, int, bytes], set[str]] = defaultdict(set)
        self._next_exec = 0               # execute strictly in sequence order
        self._exec_queue: dict[int, bytes] = {}
        # recently-executed digests (bounded): a late/duplicate T_REQUEST
        # for an already-executed command must not re-insert _commands
        # entries that nothing will ever prune
        self._executed_digests: deque = deque(maxlen=4096)
        self._executed_set: set[bytes] = set()
        # digest -> signed reply of the executed command (same bound/
        # eviction as _executed_digests): a client RETRYING an executed
        # command — its reply was lost, or it re-submits after a crash —
        # gets the cached attestation instead of silence (the reference's
        # BFT-SMaRt replies from its request log the same way)
        self._executed_replies: dict[bytes, bytes] = {}
        # ----- view-change state
        self._view_timeout_s = view_timeout_s
        self._pending_since: dict[bytes, float] = {}  # digest -> arrival time
        self._vc_msgs: dict[int, dict[str, tuple[bytes, bytes]]] = defaultdict(dict)
        self._vc_sent_for = 0             # highest view we demanded
        self._vc_last_sent = 0.0
        self._stop = threading.Event()
        self._timer: threading.Thread | None = None
        for topic, h in (
            (T_REQUEST, self._on_request), (T_PREPREPARE, self._on_preprepare),
            (T_PREPARE, self._on_prepare), (T_COMMIT, self._on_commit),
            (T_VIEWCHANGE, self._on_viewchange), (T_NEWVIEW, self._on_newview),
        ):
            messaging.add_handler(topic, auto_ack(h))
        self._start_timer()

    # ------------------------------------------------------------ lifecycle

    def _start_timer(self) -> None:
        self._timer = threading.Thread(
            target=self._timer_loop, daemon=True, name=f"bft-timer-{self.name}"
        )
        self._timer.start()

    def stop(self) -> None:
        self._stop.set()
        if self._timer is not None:
            self._timer.join(timeout=2)

    def primary_of(self, view: int) -> str:
        return self.names[view % self.n]

    @property
    def is_primary(self) -> bool:
        return self.name == self.primary_of(self.view)

    MAX_PENDING_COMMANDS = 10_000

    def _bound_pending_locked(self) -> None:
        """Cap _commands/_client_of (caller holds the lock): requests the
        primary never orders (primary down, client gave up) must not grow
        memory forever. Evicts oldest-inserted first; a legitimately
        pending command that gets evicted is restored by the client's
        retry broadcast."""
        while len(self._commands) > self.MAX_PENDING_COMMANDS:
            oldest = next(iter(self._commands))
            self._commands.pop(oldest, None)
            self._client_of.pop(oldest, None)
            self._pending_since.pop(oldest, None)

    def _multicast(self, topic: str, obj) -> None:
        payload = serialize(obj)
        for peer in self.names:
            if peer != self.name:
                self._messaging.send(peer, topic, payload)

    # ------------------------------------------------------------ phases

    def _on_request(self, msg) -> None:
        req = deserialize(msg.payload)
        command = req["command"]
        d = _digest(command)
        with self._lock:
            if d in self._executed_set:
                # duplicate of an EXECUTED command: re-send the cached
                # signed reply — the client is retrying because its
                # original replies were lost, and silence here would
                # strand an idempotent resubmission forever
                reply = self._executed_replies.get(d)
                if reply is not None:
                    self._messaging.send(req["client"], T_REPLY, reply)
                return
            self._commands[d] = command
            self._client_of[d] = req["client"]
            self._pending_since.setdefault(d, time.monotonic())
            self._bound_pending_locked()
            if not self.is_primary:
                return
            view = self.view
            seq = self._seq
            self._seq += 1
            self._preprepared[(view, seq)] = d
            self._prepares[(view, seq, d)].add(self.name)
        self._multicast(T_PREPREPARE, {"view": view, "seq": seq, "digest": d,
                                       "command": command,
                                       "client": req["client"]})
        self._check_prepared(view, seq)

    def _on_preprepare(self, msg) -> None:
        pp = deserialize(msg.payload)
        view, seq, d = pp["view"], pp["seq"], pp["digest"]
        if msg.sender != self.primary_of(view):
            return  # only that view's primary may pre-prepare
        if _digest(pp["command"]) != d:
            return  # Byzantine primary: digest mismatch
        with self._lock:
            if view < self.view or seq < self._next_exec:
                return  # stale view, or already executed and pruned
            existing = self._preprepared.get((view, seq))
            if existing is not None and existing != d:
                return  # primary equivocation: keep the first
            self._preprepared[(view, seq)] = d
            self._commands[d] = pp["command"]
            self._client_of[d] = pp["client"]
            self._pending_since.setdefault(d, time.monotonic())
            self._prepares[(view, seq, d)].add(self.name)
            self._prepares[(view, seq, d)].add(msg.sender)
        self._multicast(T_PREPARE, {"view": view, "seq": seq, "digest": d})
        self._check_prepared(view, seq)

    def _on_prepare(self, msg) -> None:
        p = deserialize(msg.payload)
        view, seq, d = p["view"], p["seq"], p["digest"]
        with self._lock:
            if view < self.view or seq < self._next_exec:
                return
            # future-view votes are tallied too: after a NEW-VIEW installs
            # that view, the early votes count instead of being lost
            self._prepares[(view, seq, d)].add(msg.sender)
        self._check_prepared(view, seq)

    def _check_prepared(self, view: int, seq: int) -> None:
        with self._lock:
            # prepared: our pre-prepare's digest gathered 2f+1 prepares
            # (incl. own); then cast our commit vote once
            d = self._preprepared.get((view, seq))
            if (d is not None
                    and len(self._prepares[(view, seq, d)]) >= 2 * self.f + 1
                    and self.name not in self._commits[(view, seq, d)]):
                self._commits[(view, seq, d)].add(self.name)
            else:
                return
        self._multicast(T_COMMIT, {"view": view, "seq": seq, "digest": d})
        self._check_committed(view, seq)

    def _on_commit(self, msg) -> None:
        c = deserialize(msg.payload)
        view, seq, d = c["view"], c["seq"], c["digest"]
        with self._lock:
            if view < self.view or seq < self._next_exec:
                return
            self._commits[(view, seq, d)].add(msg.sender)
        self._check_prepared(view, seq)
        self._check_committed(view, seq)

    def _check_committed(self, view: int, seq: int) -> None:
        with self._lock:
            d = self._preprepared.get((view, seq))
            if (d is not None
                    and len(self._commits[(view, seq, d)]) >= 2 * self.f + 1
                    and seq >= self._next_exec
                    and seq not in self._exec_queue):
                self._exec_queue[seq] = d
            to_run = []
            while self._next_exec in self._exec_queue:
                seq_i = self._next_exec
                d_i = self._exec_queue.pop(seq_i)
                # a client retry can order the same digest under two
                # sequence numbers; the first execution pruned the command,
                # so the duplicate slot is a no-op (commit is idempotent
                # per tx anyway). Null slots (view-change gap fill) skip.
                command_i = (
                    self._commands.get(d_i) if d_i != _NULL_DIGEST else None
                )
                if command_i is not None:
                    to_run.append((seq_i, d_i, command_i,
                                   self._client_of.get(d_i)))
                self._next_exec += 1
                # prune per-sequence protocol state (bounded memory at
                # sustained notarisation rates)
                for key in [k for k in self._preprepared if k[1] == seq_i]:
                    del self._preprepared[key]
                for store in (self._prepares, self._commits):
                    for key in [k for k in store if k[1] == seq_i]:
                        del store[key]
                self._commands.pop(d_i, None)
                self._client_of.pop(d_i, None)
                self._pending_since.pop(d_i, None)
                if d_i != _NULL_DIGEST and d_i not in self._executed_set:
                    if (len(self._executed_digests)
                            == self._executed_digests.maxlen):
                        evicted = self._executed_digests[0]
                        self._executed_set.discard(evicted)
                        self._executed_replies.pop(evicted, None)
                    self._executed_digests.append(d_i)
                    self._executed_set.add(d_i)
        for seq_i, d_i, command, client in to_run:
            self._execute(seq_i, d_i, command, client)

    def _execute(self, seq: int, d: bytes, command: bytes,
                 client: str | None) -> None:
        """Apply to the uniqueness map and reply to the client with a
        signature over the outcome (reference: Replica.verifyAndCommitTx +
        sign over the tx id, BFTNonValidatingNotaryService.kt:136-158).

        A ``("batch", [request, ...])`` command settles a whole notary
        window in this one totally-ordered slot; the outcome (the per-
        request conflict list) is deterministic across replicas because
        requests apply in batch order."""
        cmd = deserialize(command)
        if cmd[0] == "batch":
            requests = [(s, t, c) for s, t, c in cmd[1]]
            conflicts = self.base.commit_batch(requests)
            outcome = serialize({"batch": True, "conflicts": conflicts})
            sig = host_sign(self._keypair.private, outcome)
            client = client or (requests[0][2] if requests else None)
            reply = self._make_reply(d, outcome, sig)
            # _execute runs OUTSIDE _check_committed's locked region (it
            # does slow work: commit + sign + send); the reply cache it
            # feeds is read/evicted under the lock, so the write takes it
            with self._lock:
                self._executed_replies[d] = reply
            self._messaging.send(client, T_REPLY, reply)
            return
        states, tx_id, caller = cmd
        try:
            self.base.commit(states, tx_id, caller)
            conflict = None
        except NotaryError as e:
            conflict = e.conflict
        outcome = serialize({"tx_id": tx_id, "conflict": conflict})
        sig = host_sign(self._keypair.private, outcome)
        client = client or caller
        reply = self._make_reply(d, outcome, sig)
        with self._lock:
            self._executed_replies[d] = reply
        self._messaging.send(client, T_REPLY, reply)

    def _make_reply(self, d: bytes, outcome: bytes, sig) -> bytes:
        """The signed reply payload. A BLS-keyed replica adds its
        aggregate-signature SHARE over the outcome; the reply stays a
        dict on the wire, so decoders predating quorum certificates
        simply ignore the extra keys — old and new replicas interoperate
        in place and per-signer attestation blobs keep decoding
        (``batchverify.qc.decode_attestation``)."""
        reply = {"digest": d, "replica": self.name, "outcome": outcome,
                 "sig": sig, "key": self._keypair.public}
        if self._bls_keypair is not None:
            from corda_tpu.batchverify import bls

            reply["bls_sig"] = bls.sign(self._bls_keypair[1], outcome)
            reply["bls_v"] = 2
        return serialize(reply)

    # ------------------------------------------------------- view change

    def _timer_loop(self) -> None:
        """Suspicion timer: pending requests that stall past the timeout
        indict the current primary."""
        while not self._stop.wait(0.05):
            with self._lock:
                if not self._pending_since:
                    continue
                oldest = min(self._pending_since.values())
                now = time.monotonic()
                stalled = now - oldest > self._view_timeout_s
                resend_ok = now - self._vc_last_sent > self._view_timeout_s
                if stalled and resend_ok:
                    target = max(self.view + 1, self._vc_sent_for + 1)
                else:
                    continue
            self._send_viewchange(target)

    def _prepared_certs(self) -> list:
        """(view, seq, digest, command) for every slot this replica has
        PREPARED (2f+1 prepare votes) but not yet executed — what must
        survive into the new view (caller holds the lock). The view rides
        along so conflicting same-seq certs from different views resolve
        deterministically (highest view wins, PBFT's selection rule)."""
        certs = []
        for (view, seq), d in self._preprepared.items():
            if seq < self._next_exec or d == _NULL_DIGEST:
                continue
            if len(self._prepares[(view, seq, d)]) >= 2 * self.f + 1:
                cmd = self._commands.get(d)
                if cmd is not None:
                    certs.append((view, seq, d, cmd))
        return certs

    def _newview_preps(self, vcs) -> list:
        """Recompute the new view's re-proposals from the 2f+1 SIGNED
        view-change messages — every replica derives this list itself and
        NEVER trusts a primary-supplied one, so a Byzantine new primary
        cannot drop or overwrite a committed entry (any entry committed at
        an honest replica is prepared at ≥ f+1 honest replicas, hence
        certified inside every 2f+1 view-change set by quorum
        intersection). Per slot the highest-view certificate wins; gaps
        below the top fill with null no-ops. Caller holds the lock."""
        union: dict[int, tuple[int, bytes, bytes]] = {}
        for body, _sig in vcs.values():
            parsed = deserialize(body)
            for view, seq, d, cmd in parsed["certs"]:
                cur = union.get(seq)
                if cur is None or view > cur[0]:
                    union[seq] = (view, d, cmd)
        top = max(union) if union else self._next_exec - 1
        preps = []
        for seq in range(self._next_exec, top + 1):
            hit = union.get(seq)
            if hit is None:
                preps.append((seq, _NULL_DIGEST, b""))
            else:
                preps.append((seq, hit[1], hit[2]))
        return preps

    def _send_viewchange(self, target_view: int) -> None:
        with self._lock:
            if target_view <= self.view or self._vc_sent_for >= target_view:
                return
            self._vc_sent_for = target_view
            self._vc_last_sent = time.monotonic()
            body = serialize({
                "view": target_view, "sender": self.name,
                "last_exec": self._next_exec - 1,
                "certs": self._prepared_certs(),
            })
            sig = host_sign(self._keypair.private, body)
            self._vc_msgs[target_view][self.name] = (body, sig)
        self._multicast(T_VIEWCHANGE, {"body": body, "sig": sig})
        self._maybe_install_view(target_view)

    def _vc_valid(self, sender: str, body: bytes, sig: bytes) -> bool:
        key = self._replica_keys.get(sender)
        if key is None:
            # no key directory configured: fall back to channel identity
            return True
        try:
            return host_verify(key, sig, body)
        except Exception:
            return False

    def _on_viewchange(self, msg) -> None:
        vc = deserialize(msg.payload)
        body, sig = vc["body"], vc["sig"]
        parsed = deserialize(body)
        target = parsed["view"]
        sender = parsed["sender"]
        if sender != msg.sender or not self._vc_valid(sender, body, sig):
            return
        with self._lock:
            if target <= self.view:
                return
            self._vc_msgs[target][sender] = (body, sig)
            # join rule: once f+1 peers demand a higher view, a correct
            # replica joins the SMALLEST such view — one faulty replica
            # alone can never force churn
            joinable = sorted(
                v for v, msgs in self._vc_msgs.items()
                if v > self.view and len(msgs) >= self.f + 1
            )
        if joinable and self._vc_sent_for < joinable[0]:
            self._send_viewchange(joinable[0])
        self._maybe_install_view(target)

    def _maybe_install_view(self, target: int) -> None:
        """The would-be primary of ``target`` installs it once 2f+1 signed
        view-change messages (incl. its own) are in hand."""
        with self._lock:
            if (self.primary_of(target) != self.name
                    or target <= self.view
                    or len(self._vc_msgs[target]) < 2 * self.f + 1):
                return
            vcs = dict(self._vc_msgs[target])
            preps = self._newview_preps(vcs)
            newview = {"view": target, "vcs": vcs}
        self._multicast(T_NEWVIEW, newview)
        self._install_view(target, preps, as_primary=True)

    def _on_newview(self, msg) -> None:
        nv = deserialize(msg.payload)
        target = nv["view"]
        if msg.sender != self.primary_of(target):
            return
        with self._lock:
            if target <= self.view:
                return
        # validate the certificate: 2f+1 distinct signed view-changes
        valid_vcs = {}
        for sender, (body, sig) in nv["vcs"].items():
            parsed = deserialize(body)
            if parsed["sender"] == sender and parsed["view"] == target \
                    and self._vc_valid(sender, body, sig):
                valid_vcs[sender] = (body, sig)
        if len(valid_vcs) < 2 * self.f + 1:
            return
        # derive the re-proposals from the signed VCs OURSELVES — the
        # primary's own list is never trusted
        with self._lock:
            preps = self._newview_preps(valid_vcs)
        self._install_view(target, preps, as_primary=False)

    def _install_view(self, target: int, preps, as_primary: bool) -> None:
        with self._lock:
            if target <= self.view:
                return
            self.view = target
            self._vc_sent_for = max(self._vc_sent_for, target)
            for v in [v for v in self._vc_msgs if v <= target]:
                del self._vc_msgs[v]
            # give the new primary a full timeout before suspecting it
            now = time.monotonic()
            for d in self._pending_since:
                self._pending_since[d] = now
            max_seq = self._next_exec - 1
            installs = []
            for seq, d, cmd in preps:
                if seq < self._next_exec:
                    continue
                max_seq = max(max_seq, seq)
                self._preprepared[(target, seq)] = d
                if d != _NULL_DIGEST:
                    self._commands[d] = cmd
                    self._client_of.setdefault(d, "")
                    self._pending_since.setdefault(d, now)
                self._prepares[(target, seq, d)].add(self.name)
                self._prepares[(target, seq, d)].add(self.primary_of(target))
                installs.append((seq, d))
            if as_primary:
                self._seq = max_seq + 1
                # liveness: re-propose pending requests that never got a
                # sequence in the old view (clients broadcast to all
                # replicas, so the new primary holds them already)
                reproposals = []
                ordered = set(self._preprepared.values())
                for d, cmd in list(self._commands.items()):
                    if d in ordered or d in self._executed_set:
                        continue
                    seq = self._seq
                    self._seq += 1
                    self._preprepared[(target, seq)] = d
                    self._prepares[(target, seq, d)].add(self.name)
                    reproposals.append(
                        (seq, d, cmd, self._client_of.get(d, ""))
                    )
        for seq, d in installs:
            self._multicast(T_PREPARE, {"view": target, "seq": seq, "digest": d})
            self._check_prepared(target, seq)
        if as_primary:
            for seq, d, cmd, client in reproposals:
                self._multicast(T_PREPREPARE, {
                    "view": target, "seq": seq, "digest": d,
                    "command": cmd, "client": client,
                })
                self._check_prepared(target, seq)


class BFTClusterClient:
    """The client side (reference: BFTSMaRt.Client): broadcast the request,
    accept when f+1 replicas sign the *same* outcome. Retries the broadcast
    once per view-timeout so requests arriving during a view change are
    re-seeded into the new view."""

    def __init__(self, name: str, messaging, replica_names: list[str],
                 replica_keys: dict[str, PublicKey], timeout_s: float = 5.0,
                 retry_every_s: float = 1.5,
                 bls_keys: dict[str, bytes] | None = None):
        self.name = name
        self._messaging = messaging
        self._replicas = list(replica_names)
        self._keys = dict(replica_keys)
        self.f = (len(replica_names) - 1) // 3
        self._timeout_s = timeout_s
        self._retry_every_s = retry_every_s
        self._lock = threading.Lock()
        # digest -> {outcome_bytes: {replica: sig}}
        self._replies: dict[bytes, dict[bytes, dict[str, bytes]]] = {}
        self._futures: dict[bytes, Future] = {}
        # quorum certificates (docs/BATCH_VERIFY.md): with the cluster's
        # BLS membership known (name -> 48B public key, PoP-registered)
        # and the knob on, a settled round carries ONE aggregate
        # signature + signer bitmap instead of f+1 ed25519 attestations.
        # The bitmap indexes ``replica_names`` order — the cluster's
        # canonical membership ordering.
        self._bls_keys = dict(bls_keys or {})
        from corda_tpu.batchverify.qc import qc_enabled

        self._use_qc = bool(self._bls_keys) and qc_enabled()
        # digest -> {outcome_bytes: {replica: bls share}}
        self._bls_shares: dict[bytes, dict[bytes, dict[str, bytes]]] = {}
        self._qc_building: set[bytes] = set()
        messaging.add_handler(T_REPLY, auto_ack(self._on_reply))

    @property
    def bls_member_keys(self) -> list:
        """The cluster's BLS public keys in canonical (bitmap) order —
        what ``QuorumCertificate.verify`` consumes downstream."""
        if not self._bls_keys:
            return []
        return [self._bls_keys.get(r) for r in self._replicas]

    def _settle_locked(self, d: bytes, fut: Future | None = None) -> None:
        """Drop all per-digest state. Runs when the quorum resolves the
        future (the normal path), from collect()'s finally, and from the
        pending object's finalizer — so an abandoned pending (a pipelined
        window unwound before collect()) cannot leak its future and keep
        accumulating stray replica replies for the process lifetime.
        With ``fut`` given, settles only while that future is still the
        registered one — a retry of the same command re-registers the
        digest, and a stale finalizer/collect must not tear the retry's
        live future down."""
        if fut is not None and self._futures.get(d) is not fut:
            return
        self._futures.pop(d, None)
        self._replies.pop(d, None)
        self._bls_shares.pop(d, None)
        self._qc_building.discard(d)

    def _on_reply(self, msg) -> None:
        rep = deserialize(msg.payload)
        replica, outcome, sig = rep["replica"], rep["outcome"], rep["sig"]
        key = self._keys.get(replica)
        if key is None or rep["key"] != key:
            return
        try:
            if not host_verify(key, sig, outcome):
                return
        except Exception:
            return
        d = rep["digest"]
        build = None
        with self._lock:
            fut = self._futures.get(d)
            if fut is None:
                # late reply for an already-settled (or unknown) request —
                # don't recreate reply buckets for it (unbounded growth)
                return
            bucket = self._replies.setdefault(d, {}).setdefault(outcome, {})
            bucket[replica] = sig
            share = rep.get("bls_sig")
            if share is not None and replica in self._bls_keys:
                self._bls_shares.setdefault(d, {}).setdefault(
                    outcome, {}
                )[replica] = share
            if fut.done() or len(bucket) < self.f + 1:
                return
            if d in self._qc_building:
                # another reply thread is already assembling this
                # round's certificate; it will resolve the future
                return
            if self._use_qc:
                shares = dict(self._bls_shares.get(d, {}).get(outcome, {}))
                if len(shares) >= self.f + 1:
                    self._qc_building.add(d)
                    build = (dict(bucket), shares)
            if build is None:
                fut.set_result((outcome, dict(bucket), None))
                # quorum reached: state cleanup rides the resolution, not
                # a collect() that may never come
                self._settle_locked(d)
                return
        # certificate assembly runs OUTSIDE the lock: one aggregation +
        # ONE pairing-priced aggregate verify — far too slow for the
        # reply handler's locked region
        qc = self._try_build_qc(outcome, build[1])
        with self._lock:
            if self._futures.get(d) is fut and not fut.done():
                fut.set_result((outcome, build[0], qc))
                self._settle_locked(d)
            else:
                self._qc_building.discard(d)

    def _try_build_qc(self, outcome: bytes, shares: dict):
        """Aggregate f+1 BLS shares into a verified quorum certificate,
        or None — a garbage share (Byzantine replica) or an injected
        fault at ``notary.aggregate`` degrades the round to the legacy
        per-signer ed25519 attestations, never to a lost future."""
        from corda_tpu.batchverify import bls
        from corda_tpu.batchverify.qc import QuorumCertificate
        from corda_tpu.faultinject import check_site
        from corda_tpu.node.monitoring import node_metrics

        try:
            check_site("notary.aggregate")
            picked = sorted(
                (self._replicas.index(name), share)
                for name, share in shares.items()
            )
            bitmap = 0
            for i, _share in picked:
                bitmap |= 1 << i
            cert = QuorumCertificate(
                message=outcome,
                agg_sig=bls.aggregate([share for _i, share in picked]),
                bitmap=bitmap,
                n=len(self._replicas),
            )
            node_metrics().counter("notary.qc.aggregated").inc()
            if not cert.verify(self.bls_member_keys):
                raise ValueError("aggregate quorum signature rejected")
            node_metrics().counter("notary.qc.verified").inc()
            return cert
        except Exception:
            import logging

            node_metrics().counter("notary.qc.fallback").inc()
            logging.getLogger(__name__).warning(
                "quorum-certificate aggregation failed; round falls back "
                "to per-signer attestations"
            )
            return None

    def submit(self, states, tx_id, caller: str):
        """Returns (conflict_or_None, {replica: sig}) after quorum."""
        outcome, sigs, _qc = self._submit_command(
            serialize((list(states), tx_id, caller))
        )
        return outcome["conflict"], sigs

    def submit_batch(self, requests):
        """N requests in ONE total-order slot: returns (conflicts, sigs)
        where conflicts is the per-request list, after f+1 matching
        replies (matching = identical serialized conflict list, so the
        quorum certifies the whole batch outcome)."""
        outcome, sigs, _qc = self._submit_command(serialize(
            ("batch", [(list(s), t, c) for (s, t, c) in requests])
        ))
        return list(outcome["conflicts"]), sigs

    def _submit_command(self, command: bytes):
        return self._submit_command_async(command).collect()

    def _submit_command_async(self, command: bytes):
        """Broadcast the request and return a pending; ``collect()`` waits
        for the f+1 quorum, re-broadcasting once per view-timeout. The
        broadcast goes out NOW, so the cluster's three-phase rounds for
        consecutive notary windows pipeline (phases are per-sequence-slot)
        while the caller settles other windows."""
        d = _digest(command)
        fut: Future = Future()
        with self._lock:
            self._futures[d] = fut
        payload = serialize({"command": command, "client": self.name})
        from corda_tpu.flows.overload import active_overload

        ov = active_overload()
        if ov is not None:
            # the whole-cluster broadcast is ONE fresh send for budget
            # purposes: re-broadcasts below spend against it
            ov.note_send("bft.submit", self.name)
        for r in self._replicas:
            self._messaging.send(r, T_REQUEST, payload)
        client = self

        class _PendingSubmit:
            def collect(_self):
                # the quorum-wait budget starts HERE, not at dispatch: a
                # pipelined caller may dwell several windows between
                # dispatch and collect, and that dwell must not consume
                # the timeout (the slot has been replicating meanwhile)
                from corda_tpu.flows.overload import (
                    active_overload,
                    remaining_deadline,
                )

                budget = client._timeout_s
                rem = remaining_deadline()
                if rem is not None:
                    # propagated end-to-end deadline bounds the quorum
                    # wait: a round for a dead flow is not worth waiting
                    # out the full view timeout (docs/OVERLOAD.md)
                    budget = min(budget, max(0.05, rem))
                deadline = time.monotonic() + budget
                ov = active_overload()
                try:
                    while True:
                        try:
                            outcome_bytes, sigs, qc = fut.result(
                                timeout=min(
                                    client._retry_every_s,
                                    max(0.01, deadline - time.monotonic()),
                                )
                            )
                            break
                        except (TimeoutError, FutureTimeoutError):
                            # both spellings: concurrent.futures raises its
                            # own TimeoutError before Python 3.11 — the
                            # re-broadcast retry must fire on either
                            if time.monotonic() >= deadline:
                                raise
                            if ov is not None and not ov.allow_retry(
                                    "bft.submit", client.name):
                                # retry budget exhausted: skip this
                                # round's re-broadcast and keep waiting —
                                # the original request may still land a
                                # quorum, and the hard deadline bounds us
                                continue
                            for r in client._replicas:
                                client._messaging.send(r, T_REQUEST, payload)
                finally:
                    with client._lock:
                        client._settle_locked(d, fut)
                return deserialize(outcome_bytes), sigs, qc

        pending = _PendingSubmit()
        # lifecycle-tied cleanup: a pending abandoned WITHOUT collect()
        # (an earlier window's failure unwinding a pipelined caller) drops
        # its digest state when the object is garbage-collected. A time
        # horizon would be wrong here — a pipelined caller may legally
        # dwell many windows between dispatch and collect.
        import weakref

        def _abandoned(client=self, d=d, fut=fut):
            with client._lock:
                client._settle_locked(d, fut)

        weakref.finalize(pending, _abandoned)
        return pending


class BFTUniquenessProvider(UniquenessProvider):
    """UniquenessProvider face over a BFT cluster client."""

    def __init__(self, client: BFTClusterClient):
        self.client = client
        # the quorum certificate of the most recently COLLECTED round,
        # consumed exactly once by the notary service's take_qc() —
        # windows collect strictly in order (the pipeline settles window
        # N before window N+1), so one slot is enough
        self._last_qc = None

    @property
    def bls_member_keys(self) -> list:
        return self.client.bls_member_keys

    def take_qc(self):
        """Hand over (and clear) the last collected round's quorum
        certificate, or None for a QC-less round."""
        qc, self._last_qc = self._last_qc, None
        return qc

    def commit(self, states, tx_id, caller_name) -> None:
        outcome, _sigs, qc = self.client._submit_command(
            serialize((list(states), tx_id, caller_name))
        )
        self._last_qc = qc
        conflict = outcome["conflict"]
        if conflict is not None:
            raise NotaryError(
                f"input states of {tx_id} already consumed", conflict
            )

    def commit_batch(self, requests):
        """One total-order broadcast for the whole window (r2 VERDICT weak
        #4); the f+1 quorum certifies the per-request conflict list."""
        return self.commit_batch_async(requests).collect()

    def commit_batch_async(self, requests):
        """Put the window's total-order slot in flight and return — the
        three-phase broadcast for window N replicates while the notary
        pipeline verifies window N+1 on device (same stall fix as the
        Raft provider's commit_batch_async)."""
        from .uniqueness import PendingCommit

        if not requests:
            return PendingCommit([])
        pending = self.client._submit_command_async(serialize(
            ("batch", [(list(s), t, c) for (s, t, c) in requests])
        ))
        provider = self

        class _PendingBFTCommit:
            def collect(_self):
                outcome, _sigs, qc = pending.collect()
                provider._last_qc = qc
                return list(outcome["conflicts"])

        return _PendingBFTCommit()

    @staticmethod
    def make_cluster(n: int, network, prefix: str = "bft-replica",
                     view_timeout_s: float = 1.0, bls_qc: bool = True):
        """n = 3f+1 co-located replicas + a client factory.

        With ``bls_qc`` (and the CORDA_TPU_BLS_QC knob on), each replica
        also gets a BLS share key so rounds settle with one aggregate
        quorum certificate. The BLS keys derive DETERMINISTICALLY from
        the replica names: proof-of-possession verification is
        pairing-priced, and the deterministic derivation lets the
        process-wide PoP registry memoize it across the many in-process
        clusters a test session builds."""
        from corda_tpu.batchverify.qc import qc_enabled
        from corda_tpu.crypto import generate_keypair

        names = [f"{prefix}-{i}" for i in range(n)]
        keypairs = {name: generate_keypair() for name in names}
        keys = {name: kp.public for name, kp in keypairs.items()}
        bls_keypairs: dict = {}
        bls_keys: dict = {}
        if bls_qc and qc_enabled():
            from corda_tpu.batchverify import bls

            for name in names:
                pk, sk = bls.derive_keypair_from_entropy(name.encode())
                if not bls.is_registered(pk):
                    bls.register_pop(pk, bls.prove_possession(sk))
                bls_keypairs[name] = (pk, sk)
                bls_keys[name] = pk
        replicas = [
            BFTReplica(name, names, network.create_node(name), keypairs[name],
                       replica_keys=keys, view_timeout_s=view_timeout_s,
                       bls_keypair=bls_keypairs.get(name))
            for name in names
        ]

        def make_client(client_name: str) -> BFTUniquenessProvider:
            client = BFTClusterClient(
                client_name, network.create_node(client_name), names, keys,
                bls_keys=bls_keys or None,
            )
            return BFTUniquenessProvider(client)

        return replicas, make_client
