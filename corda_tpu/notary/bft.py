"""BFT-replicated uniqueness (Byzantine fault-tolerant notary cluster).

Role parity with the reference's BFT-SMaRt tier
(node/.../services/transactions/BFTSMaRt.kt:55+ — ``Client`` does
total-order submission and gathers signed replica replies;
``BFTNonValidatingNotaryService.Replica.executeCommand`` verifies and
commits, replying with a per-replica signature over the outcome; the client
accepts on a cluster signature quorum). The consensus engine the reference
outsources to the BFT-SMaRt jar is implemented here as PBFT-style
three-phase total-order broadcast (pre-prepare / prepare / commit with 2f
and 2f+1 quorums over n = 3f+1 replicas) on this framework's messaging
layer.

Scope note: view changes are not implemented — safety holds under f
Byzantine replicas (quorum intersection + signed replies), while liveness
assumes the view's primary stays up, the same operational posture the
reference's demo configs run (static view, BFTSMaRtConfig.kt). A client
that times out surfaces the failure rather than electing a new primary.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from concurrent.futures import Future

from corda_tpu.crypto import (
    KeyPair,
    PublicKey,
    sign as host_sign,
    is_valid as host_verify,
)
from corda_tpu.messaging import auto_ack
from corda_tpu.serialization import deserialize, serialize

from .uniqueness import (
    InMemoryUniquenessProvider,
    NotaryError,
    UniquenessProvider,
)

T_REQUEST = "bft.request"
T_PREPREPARE = "bft.preprepare"
T_PREPARE = "bft.prepare"
T_COMMIT = "bft.commit"
T_REPLY = "bft.reply"


def _digest(command: bytes) -> bytes:
    import hashlib

    return hashlib.sha256(command).digest()


class BFTReplica:
    """One PBFT replica executing a deterministic uniqueness state machine.

    ``names`` fixes the cluster membership and view: primary = names[0].
    f = (n - 1) // 3 replicas may be Byzantine.
    """

    def __init__(self, name: str, names: list[str], messaging, keypair: KeyPair,
                 base: UniquenessProvider | None = None):
        self.name = name
        self.names = list(names)
        self.n = len(names)
        self.f = (self.n - 1) // 3
        self._messaging = messaging
        self._keypair = keypair
        self.base = base or InMemoryUniquenessProvider()
        self._lock = threading.RLock()
        self._seq = 0                     # primary: next sequence number
        self._commands: dict[bytes, bytes] = {}   # digest -> command
        self._client_of: dict[bytes, str] = {}    # digest -> requesting client
        self._preprepared: dict[int, bytes] = {}  # seq -> digest
        # quorum tallies are keyed by (seq, digest): votes for different
        # commands at the same sequence must never be conflated, or an
        # equivocating primary could split honest replicas onto divergent
        # uniqueness maps with both sides reaching "quorum"
        self._prepares: dict[tuple[int, bytes], set[str]] = defaultdict(set)
        self._commits: dict[tuple[int, bytes], set[str]] = defaultdict(set)
        self._next_exec = 0               # execute strictly in sequence order
        self._exec_queue: dict[int, bytes] = {}
        # recently-executed digests (bounded): a late/duplicate T_REQUEST
        # for an already-executed command must not re-insert _commands
        # entries that nothing will ever prune
        self._executed_digests: deque = deque(maxlen=4096)
        self._executed_set: set[bytes] = set()
        for topic, h in (
            (T_REQUEST, self._on_request), (T_PREPREPARE, self._on_preprepare),
            (T_PREPARE, self._on_prepare), (T_COMMIT, self._on_commit),
        ):
            messaging.add_handler(topic, auto_ack(h))

    @property
    def is_primary(self) -> bool:
        return self.name == self.names[0]

    MAX_PENDING_COMMANDS = 10_000

    def _bound_pending(self) -> None:
        """Cap _commands/_client_of (caller holds the lock): requests the
        primary never orders (primary down, client gave up) must not grow
        memory forever. Evicts oldest-inserted first; a legitimately
        pending command that gets evicted is restored by the client's
        retry broadcast."""
        while len(self._commands) > self.MAX_PENDING_COMMANDS:
            oldest = next(iter(self._commands))
            self._commands.pop(oldest, None)
            self._client_of.pop(oldest, None)

    def _multicast(self, topic: str, obj) -> None:
        payload = serialize(obj)
        for peer in self.names:
            if peer != self.name:
                self._messaging.send(peer, topic, payload)

    # ------------------------------------------------------------ phases

    def _on_request(self, msg) -> None:
        req = deserialize(msg.payload)
        command = req["command"]
        d = _digest(command)
        with self._lock:
            if d in self._executed_set:
                return  # late duplicate of an executed command
            self._commands[d] = command
            self._client_of[d] = req["client"]
            self._bound_pending()
            if not self.is_primary:
                return
            seq = self._seq
            self._seq += 1
            self._preprepared[seq] = d
            self._prepares[(seq, d)].add(self.name)
        self._multicast(T_PREPREPARE, {"seq": seq, "digest": d,
                                       "command": command,
                                       "client": req["client"]})
        self._check_prepared(seq)

    def _on_preprepare(self, msg) -> None:
        pp = deserialize(msg.payload)
        if msg.sender != self.names[0]:
            return  # only the view primary may pre-prepare
        seq, d = pp["seq"], pp["digest"]
        if _digest(pp["command"]) != d:
            return  # Byzantine primary: digest mismatch
        with self._lock:
            if seq < self._next_exec:
                return  # already executed and pruned
            existing = self._preprepared.get(seq)
            if existing is not None and existing != d:
                return  # primary equivocation: keep the first
            self._preprepared[seq] = d
            self._commands[d] = pp["command"]
            self._client_of[d] = pp["client"]
            self._prepares[(seq, d)].add(self.name)
            self._prepares[(seq, d)].add(msg.sender)
        self._multicast(T_PREPARE, {"seq": seq, "digest": d})
        self._check_prepared(seq)

    def _on_prepare(self, msg) -> None:
        p = deserialize(msg.payload)
        seq, d = p["seq"], p["digest"]
        with self._lock:
            if seq < self._next_exec:
                return
            self._prepares[(seq, d)].add(msg.sender)
        self._check_prepared(seq)

    def _check_prepared(self, seq: int) -> None:
        with self._lock:
            # prepared: our pre-prepare's digest gathered 2f+1 prepares
            # (incl. own); then cast our commit vote once
            d = self._preprepared.get(seq)
            if (d is not None
                    and len(self._prepares[(seq, d)]) >= 2 * self.f + 1
                    and self.name not in self._commits[(seq, d)]):
                self._commits[(seq, d)].add(self.name)
            else:
                return
        self._multicast(T_COMMIT, {"seq": seq, "digest": d})
        self._check_committed(seq)

    def _on_commit(self, msg) -> None:
        c = deserialize(msg.payload)
        seq, d = c["seq"], c["digest"]
        with self._lock:
            if seq < self._next_exec:
                return
            self._commits[(seq, d)].add(msg.sender)
        self._check_prepared(seq)
        self._check_committed(seq)

    def _check_committed(self, seq: int) -> None:
        with self._lock:
            d = self._preprepared.get(seq)
            if (d is not None
                    and len(self._commits[(seq, d)]) >= 2 * self.f + 1
                    and seq >= self._next_exec
                    and seq not in self._exec_queue):
                self._exec_queue[seq] = d
            to_run = []
            while self._next_exec in self._exec_queue:
                seq_i = self._next_exec
                d_i = self._exec_queue.pop(seq_i)
                # a client retry can order the same digest under two
                # sequence numbers; the first execution pruned the command,
                # so the duplicate slot is a no-op (commit is idempotent
                # per tx anyway)
                command_i = self._commands.get(d_i)
                if command_i is not None:
                    to_run.append((seq_i, d_i, command_i,
                                   self._client_of.get(d_i)))
                self._next_exec += 1
                # prune per-sequence protocol state (bounded memory at
                # sustained notarisation rates)
                self._preprepared.pop(seq_i, None)
                for store in (self._prepares, self._commits):
                    for key in [k for k in store if k[0] == seq_i]:
                        del store[key]
                self._commands.pop(d_i, None)
                self._client_of.pop(d_i, None)
                if d_i not in self._executed_set:
                    if (len(self._executed_digests)
                            == self._executed_digests.maxlen):
                        self._executed_set.discard(self._executed_digests[0])
                    self._executed_digests.append(d_i)
                    self._executed_set.add(d_i)
        for seq_i, d_i, command, client in to_run:
            self._execute(seq_i, d_i, command, client)

    def _execute(self, seq: int, d: bytes, command: bytes,
                 client: str | None) -> None:
        """Apply to the uniqueness map and reply to the client with a
        signature over the outcome (reference: Replica.verifyAndCommitTx +
        sign over the tx id, BFTNonValidatingNotaryService.kt:136-158)."""
        states, tx_id, caller = deserialize(command)
        try:
            self.base.commit(states, tx_id, caller)
            conflict = None
        except NotaryError as e:
            conflict = e.conflict
        outcome = serialize({"tx_id": tx_id, "conflict": conflict})
        sig = host_sign(self._keypair.private, outcome)
        client = client or caller
        self._messaging.send(
            client, T_REPLY,
            serialize({"digest": d, "replica": self.name, "outcome": outcome,
                       "sig": sig, "key": self._keypair.public}),
        )


class BFTClusterClient:
    """The client side (reference: BFTSMaRt.Client): broadcast the request,
    accept when f+1 replicas sign the *same* outcome."""

    def __init__(self, name: str, messaging, replica_names: list[str],
                 replica_keys: dict[str, PublicKey], timeout_s: float = 5.0):
        self.name = name
        self._messaging = messaging
        self._replicas = list(replica_names)
        self._keys = dict(replica_keys)
        self.f = (len(replica_names) - 1) // 3
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        # digest -> {outcome_bytes: {replica: sig}}
        self._replies: dict[bytes, dict[bytes, dict[str, bytes]]] = {}
        self._futures: dict[bytes, Future] = {}
        messaging.add_handler(T_REPLY, auto_ack(self._on_reply))

    def _on_reply(self, msg) -> None:
        rep = deserialize(msg.payload)
        replica, outcome, sig = rep["replica"], rep["outcome"], rep["sig"]
        key = self._keys.get(replica)
        if key is None or rep["key"] != key:
            return
        try:
            if not host_verify(key, sig, outcome):
                return
        except Exception:
            return
        d = rep["digest"]
        with self._lock:
            fut = self._futures.get(d)
            if fut is None:
                # late reply for an already-settled (or unknown) request —
                # don't recreate reply buckets for it (unbounded growth)
                return
            bucket = self._replies.setdefault(d, {}).setdefault(outcome, {})
            bucket[replica] = sig
            if not fut.done() and len(bucket) >= self.f + 1:
                fut.set_result((outcome, dict(bucket)))

    def submit(self, states, tx_id, caller: str):
        """Returns (conflict_or_None, {replica: sig}) after quorum."""
        command = serialize((list(states), tx_id, caller))
        d = _digest(command)
        fut: Future = Future()
        with self._lock:
            self._futures[d] = fut
        payload = serialize({"command": command, "client": self.name})
        for r in self._replicas:
            self._messaging.send(r, T_REQUEST, payload)
        try:
            outcome_bytes, sigs = fut.result(timeout=self._timeout_s)
        finally:
            with self._lock:
                self._futures.pop(d, None)
                self._replies.pop(d, None)
        outcome = deserialize(outcome_bytes)
        return outcome["conflict"], sigs


class BFTUniquenessProvider(UniquenessProvider):
    """UniquenessProvider face over a BFT cluster client."""

    def __init__(self, client: BFTClusterClient):
        self.client = client

    def commit(self, states, tx_id, caller_name) -> None:
        conflict, _sigs = self.client.submit(states, tx_id, caller_name)
        if conflict is not None:
            raise NotaryError(
                f"input states of {tx_id} already consumed", conflict
            )

    @staticmethod
    def make_cluster(n: int, network, prefix: str = "bft-replica"):
        """n = 3f+1 co-located replicas + a client factory."""
        from corda_tpu.crypto import generate_keypair

        names = [f"{prefix}-{i}" for i in range(n)]
        keypairs = {name: generate_keypair() for name in names}
        replicas = [
            BFTReplica(name, names, network.create_node(name), keypairs[name])
            for name in names
        ]
        keys = {name: kp.public for name, kp in keypairs.items()}

        def make_client(client_name: str) -> BFTUniquenessProvider:
            client = BFTClusterClient(
                client_name, network.create_node(client_name), names, keys
            )
            return BFTUniquenessProvider(client)

        return replicas, make_client
