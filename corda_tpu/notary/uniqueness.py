"""Uniqueness providers: the consumed-state registry.

Parity with the reference's ``UniquenessProvider``
(core/.../node/services/UniquenessProvider.kt:15 — ``commit(states,
txId, callerIdentity)`` raising ``UniquenessException(Conflict)`` listing
which inputs were already consumed and by what) and
``PersistentUniquenessProvider`` (node/.../services/transactions/
PersistentUniquenessProvider.kt:92 — JPA append-only map). SQLite WAL
append-only table here; the commit is atomic — either all inputs are
marked consumed by this tx or none are.

The batch path (``commit_batch``) is the TPU-notary addition: N requests
settle in one storage round-trip, the shape the 10k-notarised-tx/sec
target needs (SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

import dataclasses
import sqlite3
import threading

from corda_tpu.crypto import SecureHash
from corda_tpu.ledger import StateRef
from corda_tpu.serialization import cbe_serializable


@cbe_serializable(name="notary.ConsumedStateDetails")
@dataclasses.dataclass(frozen=True)
class ConsumedStateDetails:
    """Who consumed a state (reference: UniquenessProvider.ConsumingTx —
    id, inputIndex, requestingParty)."""

    consuming_tx: SecureHash
    input_index: int
    requesting_party_name: str


@cbe_serializable(name="notary.UniquenessConflict")
@dataclasses.dataclass(frozen=True)
class UniquenessConflict:
    """(reference: UniquenessProvider.Conflict :21) — per-ref details of
    the prior consumption."""

    state_history: dict  # StateRef -> ConsumedStateDetails


class NotaryError(Exception):
    """(reference: NotaryException/NotaryError.Conflict)."""

    def __init__(self, message: str, conflict: UniquenessConflict | None = None):
        super().__init__(message)
        self.conflict = conflict


class UniquenessProvider:
    def commit(self, states: list[StateRef], tx_id: SecureHash,
               caller_name: str) -> None:
        raise NotImplementedError

    def commit_batch(
        self, requests: list[tuple[list[StateRef], SecureHash, str]]
    ) -> list[UniquenessConflict | None]:
        """Default batch = loop; subclasses override with one round-trip.
        Returns per-request None (committed) or the conflict. Requests
        within a batch are settled in order, so two requests spending the
        same input conflict deterministically (first wins)."""
        out: list[UniquenessConflict | None] = []
        for states, tx_id, caller in requests:
            try:
                self.commit(states, tx_id, caller)
                out.append(None)
            except NotaryError as e:
                out.append(e.conflict)
        return out


def _ref_key(ref: StateRef) -> bytes:
    return ref.txhash.bytes + ref.index.to_bytes(4, "big")


class InMemoryUniquenessProvider(UniquenessProvider):
    """Dict-backed provider for tests/mock networks."""

    def __init__(self):
        self._map: dict[bytes, ConsumedStateDetails] = {}
        self._lock = threading.Lock()

    def commit(self, states, tx_id, caller_name) -> None:
        with self._lock:
            conflict = {}
            for i, ref in enumerate(states):
                prior = self._map.get(_ref_key(ref))
                if prior is not None and prior.consuming_tx != tx_id:
                    conflict[ref] = prior
            if conflict:
                raise NotaryError(
                    f"input states of {tx_id} already consumed",
                    UniquenessConflict(conflict),
                )
            for i, ref in enumerate(states):
                self._map.setdefault(
                    _ref_key(ref), ConsumedStateDetails(tx_id, i, caller_name)
                )


class PersistentUniquenessProvider(UniquenessProvider):
    """SQLite append-only committed-states map (reference:
    PersistentUniquenessProvider.kt:92). Re-notarisation of the same tx is
    idempotent — returning success, the reference's behavior, so a client
    retrying after a lost response gets its signature."""

    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS notary_commits ("
            " state_key BLOB PRIMARY KEY,"
            " consuming_tx BLOB NOT NULL, input_index INTEGER NOT NULL,"
            " caller TEXT NOT NULL)"
        )
        self._db.commit()
        self._lock = threading.Lock()

    def commit(self, states, tx_id, caller_name) -> None:
        conflicts = self.commit_batch([(states, tx_id, caller_name)])[0]
        if conflicts is not None:
            raise NotaryError(
                f"input states of {tx_id} already consumed", conflicts
            )

    def commit_batch(self, requests):
        out = []
        with self._lock:
            for states, tx_id, caller in requests:
                conflict = {}
                for ref in states:
                    row = self._db.execute(
                        "SELECT consuming_tx, input_index, caller"
                        " FROM notary_commits WHERE state_key=?",
                        (_ref_key(ref),),
                    ).fetchone()
                    if row is not None and row[0] != tx_id.bytes:
                        conflict[ref] = ConsumedStateDetails(
                            SecureHash(row[0]), row[1], row[2]
                        )
                if conflict:
                    self._db.rollback()
                    out.append(UniquenessConflict(conflict))
                    continue
                for i, ref in enumerate(states):
                    self._db.execute(
                        "INSERT OR IGNORE INTO notary_commits VALUES (?,?,?,?)",
                        (_ref_key(ref), tx_id.bytes, i, caller),
                    )
                self._db.commit()
                out.append(None)
        return out

    def committed_count(self) -> int:
        with self._lock:
            return self._db.execute(
                "SELECT COUNT(*) FROM notary_commits"
            ).fetchone()[0]

    def close(self) -> None:
        with self._lock:
            self._db.close()
