"""Uniqueness providers: the consumed-state registry.

Parity with the reference's ``UniquenessProvider``
(core/.../node/services/UniquenessProvider.kt:15 — ``commit(states,
txId, callerIdentity)`` raising ``UniquenessException(Conflict)`` listing
which inputs were already consumed and by what) and
``PersistentUniquenessProvider`` (node/.../services/transactions/
PersistentUniquenessProvider.kt:92 — JPA append-only map). SQLite WAL
append-only table here; the commit is atomic — either all inputs are
marked consumed by this tx or none are.

The batch path (``commit_batch``) is the TPU-notary addition: N requests
settle in one storage round-trip, the shape the 10k-notarised-tx/sec
target needs (SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

import dataclasses
import sqlite3
import threading

from corda_tpu.crypto import SecureHash
from corda_tpu.ledger import StateRef
from corda_tpu.serialization import cbe_serializable


@cbe_serializable(name="notary.ConsumedStateDetails")
@dataclasses.dataclass(frozen=True)
class ConsumedStateDetails:
    """Who consumed a state (reference: UniquenessProvider.ConsumingTx —
    id, inputIndex, requestingParty)."""

    consuming_tx: SecureHash
    input_index: int
    requesting_party_name: str


@cbe_serializable(name="notary.UniquenessConflict")
@dataclasses.dataclass(frozen=True)
class UniquenessConflict:
    """(reference: UniquenessProvider.Conflict :21) — per-ref details of
    the prior consumption."""

    state_history: dict  # StateRef -> ConsumedStateDetails


class NotaryError(Exception):
    """(reference: NotaryException/NotaryError.Conflict)."""

    def __init__(self, message: str, conflict: UniquenessConflict | None = None):
        super().__init__(message)
        self.conflict = conflict


class PendingCommit:
    """A batch commit already settled (or already in flight): ``collect()``
    yields the per-request conflict list."""

    __slots__ = ("_conflicts",)

    def __init__(self, conflicts):
        self._conflicts = conflicts

    def collect(self):
        return self._conflicts


class UniquenessProvider:
    def commit(self, states: list[StateRef], tx_id: SecureHash,
               caller_name: str) -> None:
        raise NotImplementedError

    def commit_batch(
        self, requests: list[tuple[list[StateRef], SecureHash, str]]
    ) -> list[UniquenessConflict | None]:
        """Default batch = loop; subclasses override with one round-trip.
        Returns per-request None (committed) or the conflict. Requests
        within a batch are settled in order, so two requests spending the
        same input conflict deterministically (first wins)."""
        out: list[UniquenessConflict | None] = []
        for states, tx_id, caller in requests:
            try:
                self.commit(states, tx_id, caller)
                out.append(None)
            except NotaryError as e:
                out.append(e.conflict)
        return out

    def commit_batch_async(self, requests) -> PendingCommit:
        """Enqueue the batch commit; ``collect()`` on the returned pending
        yields the conflict list. Local providers settle eagerly (a map or
        SQLite round-trip is sub-ms — nothing to overlap); the consensus
        providers (raft/bft) override this to put a full replication round
        in flight, which the batched notary's pipeline overlaps with the
        NEXT window's device signature checks (the ``process_stream``
        depth pattern) instead of serializing on it."""
        return PendingCommit(self.commit_batch(requests))


def _ref_key(ref: StateRef) -> bytes:
    return ref.txhash.bytes + ref.index.to_bytes(4, "big")


class InMemoryUniquenessProvider(UniquenessProvider):
    """Dict-backed provider for tests/mock networks."""

    def __init__(self):
        self._map: dict[bytes, ConsumedStateDetails] = {}
        self._lock = threading.Lock()

    def commit(self, states, tx_id, caller_name) -> None:
        with self._lock:
            conflict = {}
            for i, ref in enumerate(states):
                prior = self._map.get(_ref_key(ref))
                if prior is not None and prior.consuming_tx != tx_id:
                    conflict[ref] = prior
            if conflict:
                raise NotaryError(
                    f"input states of {tx_id} already consumed",
                    UniquenessConflict(conflict),
                )
            for i, ref in enumerate(states):
                self._map.setdefault(
                    _ref_key(ref), ConsumedStateDetails(tx_id, i, caller_name)
                )

    def committed_txs(self) -> int:
        """Distinct transactions committed (ops/loadtest observability)."""
        with self._lock:
            return len({d.consuming_tx for d in self._map.values()})


class PersistentUniquenessProvider(UniquenessProvider):
    """SQLite append-only committed-states map (reference:
    PersistentUniquenessProvider.kt:92). Re-notarisation of the same tx is
    idempotent — returning success, the reference's behavior, so a client
    retrying after a lost response gets its signature."""

    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS notary_commits ("
            " state_key BLOB PRIMARY KEY,"
            " consuming_tx BLOB NOT NULL, input_index INTEGER NOT NULL,"
            " caller TEXT NOT NULL)"
        )
        self._db.commit()
        self._lock = threading.Lock()

    def commit(self, states, tx_id, caller_name) -> None:
        conflicts = self.commit_batch([(states, tx_id, caller_name)])[0]
        if conflicts is not None:
            raise NotaryError(
                f"input states of {tx_id} already consumed", conflicts
            )

    def commit_batch(self, requests):
        """All requests settle in ONE storage round-trip: a single batched
        SELECT over every referenced state key, in-memory conflict
        resolution (batch order decides intra-batch double-spends), one
        executemany INSERT, one commit/fsync — the shape the ≥10k
        notarised-tx/sec target needs."""
        out = []
        with self._lock:
            # one SELECT for the whole batch
            all_keys = sorted({
                _ref_key(ref) for states, _, _ in requests for ref in states
            })
            prior: dict = {}
            CHUNK = 512  # sqlite bind-parameter limit safety
            for i in range(0, len(all_keys), CHUNK):
                chunk = all_keys[i:i + CHUNK]
                marks = ",".join("?" * len(chunk))
                for row in self._db.execute(
                    "SELECT state_key, consuming_tx, input_index, caller"
                    f" FROM notary_commits WHERE state_key IN ({marks})",
                    chunk,
                ):
                    prior[row[0]] = (row[1], row[2], row[3])
            # settle in order; newly-consumed keys conflict later requests
            to_insert = []
            for states, tx_id, caller in requests:
                conflict = {}
                for ref in states:
                    key = _ref_key(ref)
                    hit = prior.get(key)
                    if hit is not None and hit[0] != tx_id.bytes:
                        conflict[ref] = ConsumedStateDetails(
                            SecureHash(hit[0]), hit[1], hit[2]
                        )
                if conflict:
                    out.append(UniquenessConflict(conflict))
                    continue
                for i, ref in enumerate(states):
                    key = _ref_key(ref)
                    if key not in prior:
                        to_insert.append((key, tx_id.bytes, i, caller))
                        prior[key] = (tx_id.bytes, i, caller)
                out.append(None)
            if to_insert:
                self._db.executemany(
                    "INSERT OR IGNORE INTO notary_commits VALUES (?,?,?,?)",
                    to_insert,
                )
            self._db.commit()
        return out

    def committed_count(self) -> int:
        with self._lock:
            return self._db.execute(
                "SELECT COUNT(*) FROM notary_commits"
            ).fetchone()[0]

    def committed_txs(self) -> int:
        """Distinct transactions committed (ops/loadtest observability)."""
        with self._lock:
            return self._db.execute(
                "SELECT COUNT(DISTINCT consuming_tx) FROM notary_commits"
            ).fetchone()[0]

    def close(self) -> None:
        with self._lock:
            self._db.close()
