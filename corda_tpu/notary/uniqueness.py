"""Uniqueness providers: the consumed-state registry.

Parity with the reference's ``UniquenessProvider``
(core/.../node/services/UniquenessProvider.kt:15 — ``commit(states,
txId, callerIdentity)`` raising ``UniquenessException(Conflict)`` listing
which inputs were already consumed and by what) and
``PersistentUniquenessProvider`` (node/.../services/transactions/
PersistentUniquenessProvider.kt:92 — JPA append-only map). SQLite WAL
append-only table here; the commit is atomic — either all inputs are
marked consumed by this tx or none are.

The batch path (``commit_batch``) is the TPU-notary addition: N requests
settle in one storage round-trip, the shape the 10k-notarised-tx/sec
target needs (SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

import dataclasses
import sqlite3
import threading

from corda_tpu.crypto import SecureHash
from corda_tpu.ledger import StateRef
from corda_tpu.serialization import cbe_serializable


@cbe_serializable(name="notary.ConsumedStateDetails")
@dataclasses.dataclass(frozen=True)
class ConsumedStateDetails:
    """Who consumed a state (reference: UniquenessProvider.ConsumingTx —
    id, inputIndex, requestingParty)."""

    consuming_tx: SecureHash
    input_index: int
    requesting_party_name: str


@cbe_serializable(name="notary.UniquenessConflict")
@dataclasses.dataclass(frozen=True)
class UniquenessConflict:
    """(reference: UniquenessProvider.Conflict :21) — per-ref details of
    the prior consumption."""

    state_history: dict  # StateRef -> ConsumedStateDetails


class NotaryError(Exception):
    """(reference: NotaryException/NotaryError.Conflict)."""

    def __init__(self, message: str, conflict: UniquenessConflict | None = None):
        super().__init__(message)
        self.conflict = conflict


class PendingCommit:
    """A batch commit already settled (or already in flight): ``collect()``
    yields the per-request conflict list."""

    __slots__ = ("_conflicts",)

    def __init__(self, conflicts):
        self._conflicts = conflicts

    def collect(self):
        return self._conflicts


class UniquenessProvider:
    def commit(self, states: list[StateRef], tx_id: SecureHash,
               caller_name: str) -> None:
        raise NotImplementedError

    def commit_batch(
        self, requests: list[tuple[list[StateRef], SecureHash, str]]
    ) -> list[UniquenessConflict | None]:
        """Default batch = loop; subclasses override with one round-trip.
        Returns per-request None (committed) or the conflict. Requests
        within a batch are settled in order, so two requests spending the
        same input conflict deterministically (first wins)."""
        out: list[UniquenessConflict | None] = []
        for states, tx_id, caller in requests:
            try:
                self.commit(states, tx_id, caller)
                out.append(None)
            except NotaryError as e:
                out.append(e.conflict)
        return out

    def commit_batch_async(self, requests) -> PendingCommit:
        """Enqueue the batch commit; ``collect()`` on the returned pending
        yields the conflict list. Local providers settle eagerly (a map or
        SQLite round-trip is sub-ms — nothing to overlap); the consensus
        providers (raft/bft) override this to put a full replication round
        in flight, which the batched notary's pipeline overlaps with the
        NEXT window's device signature checks (the ``process_stream``
        depth pattern) instead of serializing on it."""
        return PendingCommit(self.commit_batch(requests))


def _ref_key(ref: StateRef) -> bytes:
    return ref.txhash.bytes + ref.index.to_bytes(4, "big")


class InMemoryUniquenessProvider(UniquenessProvider):
    """Dict-backed provider for tests/mock networks."""

    def __init__(self):
        self._map: dict[bytes, ConsumedStateDetails] = {}
        self._lock = threading.Lock()

    def commit(self, states, tx_id, caller_name) -> None:
        with self._lock:
            conflict = {}
            for i, ref in enumerate(states):
                prior = self._map.get(_ref_key(ref))
                if prior is not None and prior.consuming_tx != tx_id:
                    conflict[ref] = prior
            if conflict:
                raise NotaryError(
                    f"input states of {tx_id} already consumed",
                    UniquenessConflict(conflict),
                )
            for i, ref in enumerate(states):
                self._map.setdefault(
                    _ref_key(ref), ConsumedStateDetails(tx_id, i, caller_name)
                )

    def commit_batch(self, requests):
        """Single-pass batch settle under ONE lock acquisition (the base
        class's default loops ``commit()``, re-taking the lock per
        request). Conflict reporting is pinned identical to the loop:
        requests settle in order, a committed request's keys conflict
        later requests in the same batch, and an idempotent re-commit of
        the same tx succeeds. This is the host shadow's fair A/B
        baseline for the device-sharded provider
        (docs/STATE_STORE.md)."""
        out: list[UniquenessConflict | None] = []
        with self._lock:
            for states, tx_id, caller in requests:
                conflict = {}
                for ref in states:
                    prior = self._map.get(_ref_key(ref))
                    if prior is not None and prior.consuming_tx != tx_id:
                        conflict[ref] = prior
                if conflict:
                    out.append(UniquenessConflict(conflict))
                    continue
                for i, ref in enumerate(states):
                    self._map.setdefault(
                        _ref_key(ref), ConsumedStateDetails(tx_id, i, caller)
                    )
                out.append(None)
        return out

    def committed_txs(self) -> int:
        """Distinct transactions committed (ops/loadtest observability)."""
        with self._lock:
            return len({d.consuming_tx for d in self._map.values()})

    def consumed_digest(self) -> str:
        """Same formula as ``DurableUniquenessProvider.consumed_digest``
        — this provider is the never-crashed host-map ORACLE the
        device-sharded statestore must match bit-for-bit."""
        import hashlib

        h = hashlib.sha256()
        with self._lock:
            for key in sorted(self._map):
                d = self._map[key]
                h.update(key)
                h.update(d.consuming_tx.bytes)
                h.update(d.input_index.to_bytes(4, "big"))
                h.update(d.requesting_party_name.encode())
        return h.hexdigest()


class DurableUniquenessProvider(UniquenessProvider):
    """In-memory consumed-set map journaled through a durability
    ``DurableStore`` (docs/DURABILITY.md): a commit is acked only after
    its WAL record — tx id + consumed input refs + caller — survived a
    group-commit fsync, so a restarted notary can neither forget an
    acked notarisation nor re-admit a spent state. The attestation
    *signatures* ride the same log (``record_signature``) without their
    own fsync: losing one costs a deterministic re-sign of an
    already-committed tx id — bit-identical bytes — never a second
    attestation of new state.

    Recovery = newest snapshot + WAL replay (idempotent ``setdefault``
    apply, so double replay after a crash mid-snapshot/compaction is
    harmless); ``last_recovery`` keeps the report. Snapshots fire every
    ``snapshot_every`` appended records, on the committing thread."""

    def __init__(self, store):
        self._store = store
        self._lock = threading.Lock()
        self._map: dict[bytes, ConsumedStateDetails] = {}
        self._signatures: dict = {}          # tx id -> TransactionSignature
        # LSN of the last record reflected in the in-memory state,
        # maintained under the SAME lock as the map: a snapshot claims
        # coverage of exactly this, never of a rival thread's later
        # append it did not capture
        self._last_lsn = -1
        self.last_recovery = store.recover(self._apply, self._load_snapshot)
        self._last_lsn = max(self._last_lsn, store.wal.durable_lsn)

    # ------------------------------------------------------------ recovery
    def _apply(self, rec: dict) -> None:
        with self._lock:
            if rec["k"] == "commit":
                tx_id, caller = rec["tx"], rec["caller"]
                for i, ref in enumerate(rec["refs"]):
                    self._map.setdefault(
                        _ref_key(ref), ConsumedStateDetails(tx_id, i, caller)
                    )
            elif rec["k"] == "sig":
                self._signatures[rec["tx"]] = rec["sig"]

    def _load_snapshot(self, snap: dict) -> None:
        with self._lock:
            for key, details in snap["map"]:
                self._map[bytes(key)] = details
            for tx_id, sig in snap["sigs"]:
                self._signatures[tx_id] = sig

    def _snapshot_state(self) -> tuple[dict, int]:
        """(full state, LSN it covers) — one locked capture, so the
        returned LSN can never claim a record the state lacks."""
        with self._lock:
            return {
                "map": list(self._map.items()),
                "sigs": list(self._signatures.items()),
            }, self._last_lsn

    # ------------------------------------------------------------- commits
    def commit(self, states, tx_id, caller_name) -> None:
        conflict = self.commit_batch([(states, tx_id, caller_name)])[0]
        if conflict is not None:
            raise NotaryError(
                f"input states of {tx_id} already consumed", conflict
            )

    def commit_batch(self, requests):
        out: list[UniquenessConflict | None] = []
        appended = False
        with self._lock:
            for states, tx_id, caller in requests:
                conflict = {}
                for ref in states:
                    prior = self._map.get(_ref_key(ref))
                    if prior is not None and prior.consuming_tx != tx_id:
                        conflict[ref] = prior
                if conflict:
                    out.append(UniquenessConflict(conflict))
                    continue
                for i, ref in enumerate(states):
                    self._map.setdefault(
                        _ref_key(ref), ConsumedStateDetails(tx_id, i, caller)
                    )
                self._last_lsn = self._store.append({
                    "k": "commit", "tx": tx_id, "refs": list(states),
                    "caller": caller,
                })
                appended = True
                out.append(None)
        if appended:
            # group commit OUTSIDE the map lock: concurrent windows keep
            # resolving conflicts while this fsync covers them all; the
            # ack (returning to the caller) waits for durability
            self._store.flush()
        if self._store.snapshot_due():
            state, lsn = self._snapshot_state()
            self._store.snapshot(state, covered_lsn=lsn)
        return out

    # -------------------------------------------------- attestation journal
    def record_signature(self, tx_id: SecureHash, sig) -> None:
        """Journal an issued attestation. Rides the NEXT group-commit
        flush (no fsync of its own — see class docstring for why that is
        safe); ``NotaryService.remember_signature`` calls this when its
        provider offers it."""
        with self._lock:
            self._signatures[tx_id] = sig
            self._last_lsn = self._store.append(
                {"k": "sig", "tx": tx_id, "sig": sig}
            )

    def recovered_signatures(self) -> dict:
        """The attestations that survived restart — ``NotaryService``
        preloads its signed cache from this, so a client retrying a
        pre-crash notarisation gets the ORIGINAL signature back without
        re-running verification."""
        with self._lock:
            return dict(self._signatures)

    # ---------------------------------------------------------- inspection
    def committed_txs(self) -> int:
        with self._lock:
            return len({d.consuming_tx for d in self._map.values()})

    def consumed_digest(self) -> str:
        """One hash over the full consumed-set (sorted key → consuming tx
        + index + caller) — the bit-identical comparison the kill-storm
        recovery harness makes against a never-crashed oracle run."""
        import hashlib

        h = hashlib.sha256()
        with self._lock:
            for key in sorted(self._map):
                d = self._map[key]
                h.update(key)
                h.update(d.consuming_tx.bytes)
                h.update(d.input_index.to_bytes(4, "big"))
                h.update(d.requesting_party_name.encode())
        return h.hexdigest()

    def snapshot_now(self) -> None:
        """Force a snapshot + compaction (tests and operator tooling; the
        commit path triggers the same every ``snapshot_every`` records)."""
        state, lsn = self._snapshot_state()
        self._store.snapshot(state, covered_lsn=lsn)

    def close(self) -> None:
        self._store.flush()
        self._store.close()


class PersistentUniquenessProvider(UniquenessProvider):
    """SQLite append-only committed-states map (reference:
    PersistentUniquenessProvider.kt:92). Re-notarisation of the same tx is
    idempotent — returning success, the reference's behavior, so a client
    retrying after a lost response gets its signature."""

    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS notary_commits ("
            " state_key BLOB PRIMARY KEY,"
            " consuming_tx BLOB NOT NULL, input_index INTEGER NOT NULL,"
            " caller TEXT NOT NULL)"
        )
        self._db.commit()
        self._lock = threading.Lock()

    def commit(self, states, tx_id, caller_name) -> None:
        conflicts = self.commit_batch([(states, tx_id, caller_name)])[0]
        if conflicts is not None:
            raise NotaryError(
                f"input states of {tx_id} already consumed", conflicts
            )

    def commit_batch(self, requests):
        """All requests settle in ONE storage round-trip: a single batched
        SELECT over every referenced state key, in-memory conflict
        resolution (batch order decides intra-batch double-spends), one
        executemany INSERT, one commit/fsync — the shape the ≥10k
        notarised-tx/sec target needs."""
        out = []
        with self._lock:
            # one SELECT for the whole batch
            all_keys = sorted({
                _ref_key(ref) for states, _, _ in requests for ref in states
            })
            prior: dict = {}
            CHUNK = 512  # sqlite bind-parameter limit safety
            for i in range(0, len(all_keys), CHUNK):
                chunk = all_keys[i:i + CHUNK]
                marks = ",".join("?" * len(chunk))
                for row in self._db.execute(
                    "SELECT state_key, consuming_tx, input_index, caller"
                    f" FROM notary_commits WHERE state_key IN ({marks})",
                    chunk,
                ):
                    prior[row[0]] = (row[1], row[2], row[3])
            # settle in order; newly-consumed keys conflict later requests
            to_insert = []
            for states, tx_id, caller in requests:
                conflict = {}
                for ref in states:
                    key = _ref_key(ref)
                    hit = prior.get(key)
                    if hit is not None and hit[0] != tx_id.bytes:
                        conflict[ref] = ConsumedStateDetails(
                            SecureHash(hit[0]), hit[1], hit[2]
                        )
                if conflict:
                    out.append(UniquenessConflict(conflict))
                    continue
                for i, ref in enumerate(states):
                    key = _ref_key(ref)
                    if key not in prior:
                        to_insert.append((key, tx_id.bytes, i, caller))
                        prior[key] = (tx_id.bytes, i, caller)
                out.append(None)
            if to_insert:
                self._db.executemany(
                    "INSERT OR IGNORE INTO notary_commits VALUES (?,?,?,?)",
                    to_insert,
                )
            self._db.commit()
        return out

    def committed_count(self) -> int:
        with self._lock:
            return self._db.execute(
                "SELECT COUNT(*) FROM notary_commits"
            ).fetchone()[0]

    def committed_txs(self) -> int:
        """Distinct transactions committed (ops/loadtest observability)."""
        with self._lock:
            return self._db.execute(
                "SELECT COUNT(DISTINCT consuming_tx) FROM notary_commits"
            ).fetchone()[0]

    def close(self) -> None:
        with self._lock:
            self._db.close()
