"""Durable storage for the Raft notary cluster.

The reference outsources durability to Copycat's storage module
(node/.../transactions/RaftUniquenessProvider.kt:4-17 — log + snapshots on
disk so a restarted replica rejoins with its term/vote/log intact). Here
the same guarantees come from ONE SQLite database per replica holding three
tables:

- ``raft_meta``    — current_term, voted_for, snapshot (base, term), applied
- ``raft_log``     — the replicated log, absolute-indexed
- ``notary_commits`` — the state machine itself (the consumed-state map)

Keeping the state machine in the same database as the applied-index makes
``apply`` ATOMIC: a crash between "apply" and "mark applied" cannot happen,
so restart never double-applies or skips an entry. Snapshot/compaction is
then nearly free — the state machine IS the snapshot — so compaction just
deletes log entries at or below the applied index; a follower that lags
behind the compacted prefix receives the map itself (InstallSnapshot).

Raft's persistence contract (Raft paper §5.1, Fig. 2 "persistent state"):
term/vote persist BEFORE any reply that promises them; log entries persist
BEFORE acknowledging an append. Without the vote persistence a restarted
replica could double-vote in one term and elect two leaders — the safety
hole this module closes (VERDICT r1, missing #4).
"""

from __future__ import annotations

import sqlite3
import threading

from corda_tpu.crypto import SecureHash
from corda_tpu.ledger import StateRef

from .uniqueness import ConsumedStateDetails, UniquenessConflict


def _ref_key(ref: StateRef) -> bytes:
    return ref.txhash.bytes + ref.index.to_bytes(4, "big")


class RaftStorage:
    """Durable per-replica store; every method is one transaction."""

    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS raft_meta ("
            " key TEXT PRIMARY KEY, value BLOB)"
        )
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS raft_log ("
            " idx INTEGER PRIMARY KEY, term INTEGER NOT NULL,"
            " command BLOB NOT NULL)"
        )
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS notary_commits ("
            " state_key BLOB PRIMARY KEY,"
            " consuming_tx BLOB NOT NULL, input_index INTEGER NOT NULL,"
            " caller TEXT NOT NULL)"
        )
        self._db.commit()
        self._lock = threading.Lock()

    # ------------------------------------------------------------- meta

    def _get_meta(self, key: str, default: int) -> int:
        row = self._db.execute(
            "SELECT value FROM raft_meta WHERE key=?", (key,)
        ).fetchone()
        return int(row[0]) if row is not None else default

    def _set_meta_tx(self, key: str, value: int) -> None:
        self._db.execute(
            "INSERT INTO raft_meta VALUES (?,?) "
            "ON CONFLICT(key) DO UPDATE SET value=excluded.value",
            (key, value),
        )

    def load(self) -> dict:
        """Restore persistent state after a restart."""
        with self._lock:
            term = self._get_meta("term", 0)
            voted_raw = self._db.execute(
                "SELECT value FROM raft_meta WHERE key='voted_for'"
            ).fetchone()
            voted_for = (
                voted_raw[0].decode()
                if voted_raw is not None and voted_raw[0] is not None
                and voted_raw[0] != b""
                else None
            )
            base = self._get_meta("snap_base", 0)
            snap_term = self._get_meta("snap_term", 0)
            applied = self._get_meta("applied", -1)
            entries = [
                (int(t), bytes(c))
                for (t, c) in self._db.execute(
                    "SELECT term, command FROM raft_log ORDER BY idx"
                )
            ]
            return {
                "term": term, "voted_for": voted_for, "base": base,
                "snap_term": snap_term, "applied": applied,
                "entries": entries,
            }

    def save_term_vote(self, term: int, voted_for: str | None) -> None:
        """MUST complete before granting a vote or replying with the term."""
        with self._lock:
            self._set_meta_tx("term", term)
            self._db.execute(
                "INSERT INTO raft_meta VALUES ('voted_for', ?) "
                "ON CONFLICT(key) DO UPDATE SET value=excluded.value",
                (voted_for.encode() if voted_for is not None else b"",),
            )
            self._db.commit()

    # -------------------------------------------------------------- log

    def append(self, abs_idx: int, term: int, command: bytes) -> None:
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO raft_log VALUES (?,?,?)",
                (abs_idx, term, command),
            )
            self._db.commit()

    def replace_suffix(self, start_abs_idx: int, rows: list) -> None:
        """Truncate the log from ``start_abs_idx`` and append ``rows``
        ((term, command) pairs) — one transaction, the follower-side
        conflict-resolution write."""
        with self._lock:
            self._db.execute(
                "DELETE FROM raft_log WHERE idx >= ?", (start_abs_idx,)
            )
            self._db.executemany(
                "INSERT INTO raft_log VALUES (?,?,?)",
                [
                    (start_abs_idx + i, t, c)
                    for i, (t, c) in enumerate(rows)
                ],
            )
            self._db.commit()

    # ----------------------------------------------------- state machine

    def apply_commit(
        self, abs_idx: int, states: list, tx_id: SecureHash, caller: str
    ) -> UniquenessConflict | None:
        """Apply one committed entry atomically with the applied marker.
        Idempotent: re-applying an index at or below ``applied`` (restart
        replay) is a no-op returning None."""
        with self._lock:
            if abs_idx <= self._get_meta("applied", -1):
                return None
            conflict: dict = {}
            for ref in states:
                row = self._db.execute(
                    "SELECT consuming_tx, input_index, caller FROM"
                    " notary_commits WHERE state_key=?", (_ref_key(ref),)
                ).fetchone()
                if row is not None and row[0] != tx_id.bytes:
                    conflict[ref] = ConsumedStateDetails(
                        SecureHash(row[0]), row[1], row[2]
                    )
            if not conflict:
                self._db.executemany(
                    "INSERT OR IGNORE INTO notary_commits VALUES (?,?,?,?)",
                    [
                        (_ref_key(ref), tx_id.bytes, i, caller)
                        for i, ref in enumerate(states)
                    ],
                )
            self._set_meta_tx("applied", abs_idx)
            self._db.commit()
            return UniquenessConflict(conflict) if conflict else None

    def apply_commit_batch(
        self, abs_idx: int, requests: list
    ) -> list[UniquenessConflict | None]:
        """Apply ONE committed log entry carrying N commit requests — one
        transaction, one applied-marker write, one fsync (the clustered
        notary's answer to per-tx consensus: the reference's Raft map is
        batched per tx via putAll, DistributedImmutableMap.kt; this goes
        wider — a whole notary window per entry). Requests settle in
        order, so intra-batch double-spends conflict deterministically on
        every replica. Idempotent on replay like ``apply_commit``."""
        with self._lock:
            if abs_idx <= self._get_meta("applied", -1):
                return [None] * len(requests)
            out: list[UniquenessConflict | None] = []
            prior: dict[bytes, tuple] = {}
            to_insert = []
            for states, tx_id, caller in requests:
                conflict: dict = {}
                for ref in states:
                    key = _ref_key(ref)
                    hit = prior.get(key)
                    if hit is None:
                        row = self._db.execute(
                            "SELECT consuming_tx, input_index, caller FROM"
                            " notary_commits WHERE state_key=?", (key,)
                        ).fetchone()
                        if row is not None:
                            hit = (bytes(row[0]), row[1], row[2])
                            prior[key] = hit
                    if hit is not None and hit[0] != tx_id.bytes:
                        conflict[ref] = ConsumedStateDetails(
                            SecureHash(hit[0]), hit[1], hit[2]
                        )
                if conflict:
                    out.append(UniquenessConflict(conflict))
                    continue
                for i, ref in enumerate(states):
                    key = _ref_key(ref)
                    if key not in prior:
                        to_insert.append((key, tx_id.bytes, i, caller))
                        prior[key] = (tx_id.bytes, i, caller)
                out.append(None)
            if to_insert:
                self._db.executemany(
                    "INSERT OR IGNORE INTO notary_commits VALUES (?,?,?,?)",
                    to_insert,
                )
            self._set_meta_tx("applied", abs_idx)
            self._db.commit()
            return out

    def compact(self, upto_abs_idx: int, upto_term: int) -> None:
        """Drop log entries ≤ ``upto_abs_idx`` — the state machine already
        reflects them (it IS the snapshot)."""
        with self._lock:
            self._db.execute(
                "DELETE FROM raft_log WHERE idx <= ?", (upto_abs_idx,)
            )
            self._set_meta_tx("snap_base", upto_abs_idx + 1)
            self._set_meta_tx("snap_term", upto_term)
            self._db.commit()

    # ------------------------------------------------ snapshot transfer

    def dump_map(self) -> list:
        """Serialize the consumed-state map for InstallSnapshot."""
        with self._lock:
            return [
                (bytes(k), bytes(t), i, c)
                for (k, t, i, c) in self._db.execute(
                    "SELECT state_key, consuming_tx, input_index, caller"
                    " FROM notary_commits"
                )
            ]

    def install_snapshot(
        self, rows: list, last_idx: int, last_term: int
    ) -> None:
        """Replace the whole state machine + log with a leader snapshot —
        one transaction, so a crash mid-install leaves the old state."""
        with self._lock:
            self._db.execute("DELETE FROM notary_commits")
            self._db.executemany(
                "INSERT INTO notary_commits VALUES (?,?,?,?)", rows
            )
            self._db.execute("DELETE FROM raft_log")
            self._set_meta_tx("applied", last_idx)
            self._set_meta_tx("snap_base", last_idx + 1)
            self._set_meta_tx("snap_term", last_term)
            self._db.commit()

    # ------------------------------------------------------- inspection

    def committed_txs(self) -> int:
        with self._lock:
            return self._db.execute(
                "SELECT COUNT(DISTINCT consuming_tx) FROM notary_commits"
            ).fetchone()[0]

    def close(self) -> None:
        with self._lock:
            self._db.close()
