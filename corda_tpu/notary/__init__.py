"""Notary tier — uniqueness consensus (L7 of SURVEY.md §1).

Parity with the reference's node/.../services/transactions/: pluggable
``UniquenessProvider``s (in-memory, persistent, Raft-replicated,
BFT-replicated) under notary services (simple non-validating, validating,
and the TPU-batched validating notary that verifies whole request batches
as device kernels — BASELINE config #5's target).
"""

from .uniqueness import (
    DurableUniquenessProvider,
    InMemoryUniquenessProvider,
    NotaryError,
    PersistentUniquenessProvider,
    UniquenessConflict,
    UniquenessProvider,
)
from .service import (
    BatchedNotaryService,
    NotaryService,
    SimpleNotaryService,
    ValidatingNotaryService,
)
from .raft import RaftNode, RaftUniquenessProvider
from .raft_storage import RaftStorage
from .bft import BFTClusterClient, BFTReplica, BFTUniquenessProvider


def __getattr__(name: str):
    # lazy: the device-sharded provider lives in corda_tpu.statestore
    # (docs/STATE_STORE.md) and is re-exported here as a notary backend
    # without importing that package on the default path
    if name == "DeviceShardedUniquenessProvider":
        from corda_tpu.statestore import DeviceShardedUniquenessProvider

        return DeviceShardedUniquenessProvider
    raise AttributeError(name)


__all__ = [
    "DeviceShardedUniquenessProvider",
    "DurableUniquenessProvider",
    "InMemoryUniquenessProvider", "NotaryError", "PersistentUniquenessProvider",
    "UniquenessConflict", "UniquenessProvider",
    "BatchedNotaryService", "NotaryService", "SimpleNotaryService",
    "ValidatingNotaryService",
    "RaftNode", "RaftStorage", "RaftUniquenessProvider",
    "BFTClusterClient", "BFTReplica", "BFTUniquenessProvider",
]
