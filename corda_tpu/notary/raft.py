"""Raft-replicated uniqueness provider (CFT notary cluster).

Role parity with the reference's Copycat-based tier
(node/.../services/transactions/RaftUniquenessProvider.kt:4-17 +
DistributedImmutableMap.kt — a replicated put-all-or-report-conflicts map
of consumed states; RaftValidatingNotaryService / RaftNonValidatingNotary-
Service wrap it). Re-implemented from the Raft paper over this framework's
messaging layer (leader election with randomized timeouts, log replication,
majority commit, state-machine apply), because the JVM dependency is the
engine the reference outsources — here it's a first-class component.

The replicated state machine is the uniqueness map: a committed log entry
is a (states, tx_id, caller) commit request; apply() settles it against
the local map, deterministically identical on every replica.

Durability (parity with Copycat's on-disk log + snapshots): with a
``RaftStorage`` attached, term/vote persist before any reply that promises
them, log entries persist before acknowledgement, apply is atomic with the
applied-index marker, and the log COMPACTS against the durable state
machine (which is its own snapshot) — a lagging follower past the
compaction horizon receives the map itself (InstallSnapshot). Without
storage the node is a volatile test replica (full log, no compaction).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError

# Future.result(timeout=) raises concurrent.futures.TimeoutError, which is
# NOT the builtin TimeoutError before Python 3.11 — catching only the
# builtin silently disabled every submit-timeout retry on 3.10 (the
# mid-election resubmit path the chaos soak exercises).
_TIMEOUT_ERRORS = (TimeoutError, FutureTimeoutError)

from corda_tpu.messaging import auto_ack
from corda_tpu.serialization import deserialize, serialize

from .raft_storage import RaftStorage
from .uniqueness import (
    InMemoryUniquenessProvider,
    NotaryError,
    UniquenessProvider,
)

T_VOTE = "raft.vote"
T_VOTE_REPLY = "raft.vote-reply"
T_APPEND = "raft.append"
T_APPEND_REPLY = "raft.append-reply"
T_SNAPSHOT = "raft.snapshot"
T_SUBMIT = "raft.submit"
T_SUBMIT_REPLY = "raft.submit-reply"


@dataclasses.dataclass(frozen=True)
class LogEntry:
    term: int
    command: bytes  # serialized (states, tx_id, caller)


class RaftLog:
    """The replicated log with a compacted prefix.

    ``entries[0]`` sits at absolute index ``base``; everything below is
    folded into the state machine (the snapshot). ``snap_term`` is the term
    of entry ``base - 1`` — needed for the AppendEntries consistency check
    at the compaction boundary."""

    __slots__ = ("base", "snap_term", "entries")

    def __init__(self, base: int = 0, snap_term: int = 0, entries=None):
        self.base = base
        self.snap_term = snap_term
        self.entries: list[LogEntry] = list(entries or [])

    def last_index(self) -> int:
        return self.base + len(self.entries) - 1

    def last_term(self) -> int:
        return self.entries[-1].term if self.entries else self.snap_term

    def term_at(self, abs_idx: int) -> int | None:
        """Term of the entry at abs_idx; snap_term at the boundary, None
        for compacted (< base-1) or out-of-range indices."""
        if abs_idx == -1:
            return 0
        if abs_idx == self.base - 1:
            return self.snap_term
        pos = abs_idx - self.base
        if 0 <= pos < len(self.entries):
            return self.entries[pos].term
        return None

    def get(self, abs_idx: int) -> LogEntry:
        return self.entries[abs_idx - self.base]

    def slice_from(self, abs_idx: int) -> list[LogEntry]:
        return self.entries[max(0, abs_idx - self.base):]

    def append(self, e: LogEntry) -> int:
        self.entries.append(e)
        return self.last_index()

    def truncate_from(self, abs_idx: int) -> None:
        del self.entries[abs_idx - self.base:]

    def compact_to(self, abs_idx: int) -> None:
        """Drop entries ≤ abs_idx (must be ≤ applied)."""
        term = self.term_at(abs_idx)
        del self.entries[: abs_idx - self.base + 1]
        self.base = abs_idx + 1
        self.snap_term = term


class NotLeaderError(Exception):
    def __init__(self, leader: str | None):
        self.leader = leader
        super().__init__(f"not leader; known leader: {leader}")


def _retryable_submit_error(e: Exception) -> bool:
    """Leadership churn is retryable, in EVERY wrapping: a direct
    NotLeaderError, a submit timeout, or a "not leader" that travelled as
    a generic error-string reply (older peers / any wrap path). The
    substring contract with _on_submit_reply's error wrap lives here and
    only here."""
    if isinstance(e, (NotLeaderError, *_TIMEOUT_ERRORS)):
        return True
    return "not leader" in str(e)


class RaftNode:
    """One Raft replica. ``apply_fn(command_bytes, abs_index) ->
    result_bytes`` is the deterministic state machine."""

    FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

    def __init__(
        self, name: str, peers: list[str], messaging, apply_fn,
        election_timeout_s: tuple[float, float] = (0.15, 0.3),
        heartbeat_s: float = 0.05,
        rng: random.Random | None = None,
        storage: RaftStorage | None = None,
        compact_every: int = 512,
        install_map_fn=None,
    ):
        self.name = name
        self.peers = [p for p in peers if p != name]
        self._messaging = messaging
        self._apply_fn = apply_fn
        self._timeout_range = election_timeout_s
        self._heartbeat_s = heartbeat_s
        self._rng = rng or random.Random(name)
        self._storage = storage
        self._compact_every = compact_every
        self._install_map_fn = install_map_fn

        self._lock = threading.RLock()
        self.role = RaftNode.FOLLOWER
        # bounded election-storm backoff: each consecutive election that
        # fails to produce a leader doubles the next timeout draw (cap
        # ELECTION_BACKOFF_CAP×); hearing from a real leader resets it.
        # Under a partition or heavy message loss this stops the cluster
        # burning terms (and bandwidth) at the base cadence, and spreads
        # candidacies so the first heal round elects instead of splitting.
        self._elections_since_leader = 0
        self.current_term = 0
        self.voted_for: str | None = None
        self.log = RaftLog()
        self.commit_index = -1
        self.last_applied = -1
        self.leader: str | None = None
        if storage is not None:
            # restart: resume with the persisted term/vote/log; everything
            # at or below the applied marker is already in the state machine
            st = storage.load()
            self.current_term = st["term"]
            self.voted_for = st["voted_for"]
            self.log = RaftLog(
                st["base"], st["snap_term"],
                [LogEntry(t, c) for (t, c) in st["entries"]],
            )
            self.last_applied = st["applied"]
            self.commit_index = st["applied"]
        # leader volatile state
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}
        self._votes: set[str] = set()
        # client futures waiting on an index we proposed; the entry object
        # is kept alongside so a truncate-and-replace at the same index
        # after a leadership change fails the waiter instead of handing it
        # another command's result
        self._waiters: dict[int, tuple[LogEntry, Future]] = {}
        # remote submissions we're waiting on, by correlation id
        self._pending_remote: dict[str, Future] = {}
        self._corr = 0

        self._deadline = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        for topic, handler in (
            (T_VOTE, self._on_vote), (T_VOTE_REPLY, self._on_vote_reply),
            (T_APPEND, self._on_append), (T_APPEND_REPLY, self._on_append_reply),
            (T_SNAPSHOT, self._on_snapshot),
            (T_SUBMIT, self._on_submit),
            (T_SUBMIT_REPLY, self._on_submit_reply),
        ):
            messaging.add_handler(topic, auto_ack(handler))

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        with self._lock:
            self._reset_timer_locked()
        self._thread = threading.Thread(
            target=self._tick_loop, daemon=True, name=f"raft-{self.name}"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    ELECTION_BACKOFF_CAP = 8.0

    def _election_backoff(self) -> float:
        # cap the EXPONENT, not just the result: the counter grows without
        # bound during a long partition and 2.0**1024 overflows, which
        # would kill the tick thread
        return min(2.0 ** min(self._elections_since_leader, 6),
                   RaftNode.ELECTION_BACKOFF_CAP)

    def _reset_timer_locked(self) -> None:
        self._deadline = time.monotonic() + (
            self._rng.uniform(*self._timeout_range) * self._election_backoff()
        )

    def _tick_loop(self) -> None:
        while not self._stop.wait(0.01):
            with self._lock:
                now = time.monotonic()
                if self.role == RaftNode.LEADER:
                    if now >= self._deadline:
                        self._deadline = now + self._heartbeat_s
                        self._broadcast_append()
                elif now >= self._deadline:
                    self._start_election_locked()

    def _persist_term_vote(self) -> None:
        """Raft's persistence contract: term/vote are on disk BEFORE any
        message promising them leaves this replica."""
        if self._storage is not None:
            self._storage.save_term_vote(self.current_term, self.voted_for)

    # ------------------------------------------------------------ election

    def _start_election_locked(self) -> None:
        self.role = RaftNode.CANDIDATE
        self._elections_since_leader += 1
        self.current_term += 1
        self.voted_for = self.name
        self._persist_term_vote()
        self._votes = {self.name}
        self.leader = None
        self._reset_timer_locked()
        req = {"term": self.current_term, "candidate": self.name,
               "last_log_index": self.log.last_index(),
               "last_log_term": self.log.last_term()}
        for p in self.peers:
            self._messaging.send(p, T_VOTE, serialize(req))
        self._maybe_win_locked()  # single-node cluster wins immediately

    def _on_vote(self, msg) -> None:
        req = deserialize(msg.payload)
        with self._lock:
            self._observe_term_locked(req["term"])
            grant = False
            if req["term"] >= self.current_term and self.voted_for in (None, req["candidate"]):
                up_to_date = (req["last_log_term"], req["last_log_index"]) >= (
                    self.log.last_term(), self.log.last_index(),
                )
                if up_to_date:
                    grant = True
                    self.voted_for = req["candidate"]
                    self._persist_term_vote()
                    self._reset_timer_locked()
            self._messaging.send(
                msg.sender, T_VOTE_REPLY,
                serialize({"term": self.current_term, "granted": grant,
                           "voter": self.name}),
            )

    def _on_vote_reply(self, msg) -> None:
        rep = deserialize(msg.payload)
        with self._lock:
            self._observe_term_locked(rep["term"])
            if self.role != RaftNode.CANDIDATE or rep["term"] != self.current_term:
                return
            if rep["granted"]:
                self._votes.add(rep["voter"])
                self._maybe_win_locked()

    def _maybe_win_locked(self) -> None:
        if self.role == RaftNode.CANDIDATE and len(self._votes) * 2 > len(self.peers) + 1:
            self.role = RaftNode.LEADER
            self.leader = self.name
            self._elections_since_leader = 0
            n = self.log.last_index() + 1
            self._next_index = {p: n for p in self.peers}
            self._match_index = {p: -1 for p in self.peers}
            self._deadline = 0.0  # heartbeat immediately
            self._broadcast_append()

    def _observe_term_locked(self, term: int) -> None:
        if term > self.current_term:
            self.current_term = term
            self.role = RaftNode.FOLLOWER
            self.voted_for = None
            self._votes = set()
            self._persist_term_vote()

    # ------------------------------------------------------------ replication

    def _broadcast_append(self) -> None:
        for p in self.peers:
            self._send_append(p)

    def _send_append(self, peer: str) -> None:
        nxt = self._next_index.get(peer, self.log.last_index() + 1)
        if nxt < self.log.base:
            # the entries this follower needs are compacted: ship the state
            # machine itself (InstallSnapshot)
            self._send_snapshot(peer)
            return
        prev_idx = nxt - 1
        prev_term = self.log.term_at(prev_idx) or 0
        entries = [(e.term, e.command) for e in self.log.slice_from(nxt)]
        req = {
            "term": self.current_term, "leader": self.name,
            "prev_log_index": prev_idx, "prev_log_term": prev_term,
            "entries": entries, "leader_commit": self.commit_index,
        }
        self._messaging.send(peer, T_APPEND, serialize(req))

    def _send_snapshot(self, peer: str) -> None:
        assert self._storage is not None, "compaction requires storage"
        req = {
            "term": self.current_term, "leader": self.name,
            "last_idx": self.log.base - 1, "last_term": self.log.snap_term,
            "rows": self._storage.dump_map(),
        }
        self._messaging.send(peer, T_SNAPSHOT, serialize(req))

    def _on_snapshot(self, msg) -> None:
        req = deserialize(msg.payload)
        with self._lock:
            self._observe_term_locked(req["term"])
            if req["term"] != self.current_term:
                return
            installer = (
                self._storage.install_snapshot
                if self._storage is not None
                else self._install_map_fn
            )
            if installer is None:
                # no way to apply a snapshot on this replica: say so (a
                # silent drop would have the leader re-shipping the map
                # every heartbeat forever)
                self._messaging.send(
                    msg.sender, T_APPEND_REPLY,
                    serialize({"term": self.current_term, "ok": False,
                               "follower": self.name, "match_index": -1}),
                )
                return
            self.role = RaftNode.FOLLOWER
            self.leader = req["leader"]
            self._elections_since_leader = 0
            self._reset_timer_locked()
            last_idx = req["last_idx"]
            if last_idx > self.last_applied:
                installer(req["rows"], last_idx, req["last_term"])
                self.log = RaftLog(last_idx + 1, req["last_term"])
                self.last_applied = last_idx
                self.commit_index = max(self.commit_index, last_idx)
            self._messaging.send(
                msg.sender, T_APPEND_REPLY,
                serialize({"term": self.current_term, "ok": True,
                           "follower": self.name,
                           "match_index": max(last_idx, self.last_applied)}),
            )

    def _on_append(self, msg) -> None:
        req = deserialize(msg.payload)
        with self._lock:
            self._observe_term_locked(req["term"])
            ok = False
            match_index = -1
            if req["term"] == self.current_term:
                self.role = RaftNode.FOLLOWER
                self.leader = req["leader"]
                self._elections_since_leader = 0  # live leader: no storm
                self._reset_timer_locked()
                prev_idx = req["prev_log_index"]
                entries = req["entries"]
                if prev_idx < self.log.base - 1:
                    # our snapshot already covers a prefix of these
                    # entries; everything ≤ base-1 is committed+applied, so
                    # it matches any legitimate leader's log by Raft safety
                    skip = (self.log.base - 1) - prev_idx
                    entries = entries[skip:]
                    prev_idx = self.log.base - 1
                    prev_ok = True
                else:
                    prev_term = self.log.term_at(prev_idx)
                    prev_ok = prev_term is not None and prev_term == req["prev_log_term"]
                if prev_ok:
                    ok = True
                    idx = prev_idx + 1
                    first_change: int | None = None
                    for term, cmd in entries:
                        have = self.log.term_at(idx)
                        if have is not None and have != term:
                            self.log.truncate_from(idx)
                            self._fail_waiters_from_locked(idx)
                            have = None
                        if have is None and idx > self.log.last_index():
                            self.log.append(LogEntry(term, cmd))
                            if first_change is None:
                                first_change = idx
                        idx += 1
                    if first_change is not None and self._storage is not None:
                        # persist the changed suffix BEFORE acknowledging
                        self._storage.replace_suffix(
                            first_change,
                            [(e.term, e.command)
                             for e in self.log.slice_from(first_change)],
                        )
                    match_index = prev_idx + len(entries)
                    if req["leader_commit"] > self.commit_index:
                        self.commit_index = min(
                            req["leader_commit"], self.log.last_index()
                        )
                        self._apply_committed_locked()
            self._messaging.send(
                msg.sender, T_APPEND_REPLY,
                serialize({"term": self.current_term, "ok": ok,
                           "follower": self.name, "match_index": match_index}),
            )

    def _on_append_reply(self, msg) -> None:
        rep = deserialize(msg.payload)
        with self._lock:
            self._observe_term_locked(rep["term"])
            if self.role != RaftNode.LEADER or rep["term"] != self.current_term:
                return
            p = rep["follower"]
            if rep["ok"]:
                self._match_index[p] = max(self._match_index.get(p, -1),
                                           rep["match_index"])
                self._next_index[p] = self._match_index[p] + 1
                self._advance_commit_locked()
            else:
                self._next_index[p] = max(0, self._next_index.get(p, 1) - 1)
                self._send_append(p)

    def _advance_commit_locked(self) -> None:
        n = len(self.peers) + 1
        for idx in range(self.log.last_index(), self.commit_index, -1):
            if self.log.term_at(idx) != self.current_term:
                continue
            votes = 1 + sum(1 for p in self.peers if self._match_index.get(p, -1) >= idx)
            if votes * 2 > n:
                self.commit_index = idx
                self._apply_committed_locked()
                break

    def _fail_waiters_from_locked(self, idx: int) -> None:
        """A truncation invalidated every proposal at >= idx."""
        for i in [i for i in self._waiters if i >= idx]:
            _entry, fut = self._waiters.pop(i)
            if not fut.done():
                fut.set_exception(NotLeaderError(self.leader))

    def _apply_committed_locked(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self.log.get(self.last_applied)
            result = self._apply_fn(entry.command, self.last_applied)
            waiter = self._waiters.pop(self.last_applied, None)
            if waiter is not None:
                proposed, fut = waiter
                if fut.done():
                    pass
                elif proposed is entry:
                    fut.set_result(result)
                else:  # a different command landed at our index
                    fut.set_exception(NotLeaderError(self.leader))
        if (
            self._storage is not None
            and self.last_applied - self.log.base + 1 >= self._compact_every
        ):
            term = self.log.term_at(self.last_applied)
            self._storage.compact(self.last_applied, term)
            self.log.compact_to(self.last_applied)

    # ------------------------------------------------------------ client API

    def submit(self, command: bytes) -> Future:
        """Leader-only: append + replicate; future completes with the
        state-machine apply result once committed."""
        with self._lock:
            if self.role != RaftNode.LEADER:
                raise NotLeaderError(self.leader)
            entry = LogEntry(self.current_term, command)
            idx = self.log.append(entry)
            if self._storage is not None:
                # the leader's own log write must be durable before it can
                # count toward the majority
                self._storage.append(idx, entry.term, entry.command)
            fut: Future = Future()
            self._waiters[idx] = (entry, fut)
            if not self.peers:  # single-node cluster commits immediately
                self.commit_index = idx
                self._apply_committed_locked()
            else:
                self._broadcast_append()
            return fut

    def _on_submit(self, msg) -> None:
        """Remote client submission (any replica accepts; forwards result
        or redirect)."""
        req = deserialize(msg.payload)
        with self._lock:
            is_leader = self.role == RaftNode.LEADER
            leader = self.leader
        if not is_leader:
            self._messaging.send(
                msg.sender, T_SUBMIT_REPLY,
                serialize({"corr": req["corr"], "redirect": leader}),
            )
            return
        try:
            fut = self.submit(req["command"])
        except NotLeaderError as e:
            # lost leadership between the check above and the append (a
            # mid-election race): answer with a REDIRECT, not a generic
            # error — clients treat redirects as retryable, while an
            # error string propagated as a terminal NotaryError (the
            # r5 cluster-bench failure mode)
            self._messaging.send(
                msg.sender, T_SUBMIT_REPLY,
                serialize({"corr": req["corr"], "redirect": e.leader}),
            )
            return

        def done(f, corr=req["corr"], sender=msg.sender):
            try:
                self._messaging.send(
                    sender, T_SUBMIT_REPLY,
                    serialize({"corr": corr, "result": f.result()}),
                )
            except NotLeaderError as e:
                # the entry was displaced by a leadership change while
                # replicating — retryable: redirect, don't error
                self._messaging.send(
                    sender, T_SUBMIT_REPLY,
                    serialize({"corr": corr, "redirect": e.leader}),
                )
            except Exception as e:
                self._messaging.send(
                    sender, T_SUBMIT_REPLY,
                    serialize({"corr": corr, "error": str(e)}),
                )

        fut.add_done_callback(done)

    def _on_submit_reply(self, msg) -> None:
        rep = deserialize(msg.payload)
        with self._lock:
            fut = self._pending_remote.pop(rep["corr"], None)
        if fut is None or fut.done():
            return
        if "result" in rep:
            fut.set_result(rep["result"])
        elif "redirect" in rep:
            fut.set_exception(NotLeaderError(rep["redirect"]))
        else:
            fut.set_exception(NotaryError(rep.get("error", "submit failed")))

    def submit_anywhere(self, command: bytes) -> Future:
        """Submit locally when leader, else forward to the known leader (or
        probe a peer) over messaging — the CopycatClient role."""
        with self._lock:
            if self.role == RaftNode.LEADER:
                return self.submit(command)
            target = self.leader
            if target is None and self.peers:
                target = self.peers[self._corr % len(self.peers)]
            self._corr += 1
            corr = f"{self.name}-{self._corr}"
            fut: Future = Future()
            self._pending_remote[corr] = fut
        if target is None:
            fut.set_exception(NotLeaderError(None))
            return fut
        self._messaging.send(
            target, T_SUBMIT, serialize({"corr": corr, "command": command})
        )
        return fut


class RaftUniquenessProvider(UniquenessProvider):
    """UniquenessProvider face over a RaftNode whose state machine is a
    local uniqueness map (reference: RaftUniquenessProvider +
    DistributedImmutableMap). Use ``RaftUniquenessProvider.make_cluster``
    to build co-located replicas for tests/demos; pass ``storage_dir`` for
    durable replicas that survive full-cluster restarts."""

    def __init__(self, node: RaftNode):
        self.node = node
        # retry window covers one election cycle
        self._retry_s = 2.0
        # leader-change retries back off exponentially with jitter —
        # fixed-cadence retries from many clients re-synchronized into
        # the same election window are their own little storm. Seeded by
        # replica name so chaos runs reproduce.
        from corda_tpu.messaging.retry import RetryPolicy

        self._retry_policy = RetryPolicy(
            base_s=0.02, multiplier=2.0, max_backoff_s=0.4, jitter=0.5
        )
        self._retry_rng = random.Random(f"retry:{node.name}")

    @staticmethod
    def state_machine(base: UniquenessProvider | None = None):
        """Volatile state machine over an in-memory uniqueness map.

        Commands come in two shapes: a single (states, tx_id, caller)
        request, or ``("batch", [request, ...])`` — one log entry settling
        a whole notary window (apply order is the log order on every
        replica, so batch results are deterministic)."""
        base = base or InMemoryUniquenessProvider()

        def apply(command: bytes, _abs_idx: int) -> bytes:
            cmd = deserialize(command)
            if cmd[0] == "batch":
                return serialize(base.commit_batch(
                    [(s, t, c) for s, t, c in cmd[1]]
                ))
            states, tx_id, caller = cmd
            try:
                base.commit(states, tx_id, caller)
                return serialize(None)
            except NotaryError as e:
                return serialize(e.conflict)

        return apply, base

    @staticmethod
    def storage_state_machine(storage: RaftStorage):
        """Durable state machine: apply lands in the same transaction as
        the applied-index marker (exactly-once across restarts)."""

        def apply(command: bytes, abs_idx: int) -> bytes:
            cmd = deserialize(command)
            if cmd[0] == "batch":
                return serialize(storage.apply_commit_batch(
                    abs_idx, [(list(s), t, c) for s, t, c in cmd[1]]
                ))
            states, tx_id, caller = cmd
            return serialize(
                storage.apply_commit(abs_idx, list(states), tx_id, caller)
            )

        return apply

    def _submit_retrying(self, command: bytes):
        """Submit through whichever replica currently leads, riding out one
        election cycle; re-submission after an ambiguous timeout is safe —
        the state machine is idempotent per tx_id. Retries back off
        exponentially with jitter under the overall ``_retry_s`` deadline
        (the propagated budget — no attempt outlives it)."""
        from corda_tpu.flows.overload import active_overload, remaining_deadline

        budget = self._retry_s
        rem = remaining_deadline()
        if rem is not None:
            # the propagated end-to-end deadline tightens the submit
            # budget: a consensus round for a dead flow is wasted work
            # (docs/OVERLOAD.md). Small floor so an on-the-edge submit
            # fails with a timeout, not a zero-wait raise.
            budget = min(budget, max(0.05, rem))
        deadline = time.monotonic() + budget
        ov = active_overload()
        edge = str(getattr(self.node, "name", "raft"))
        if ov is not None:
            ov.note_send("raft.submit", edge)
        attempt = 0
        while True:
            try:
                fut = self.node.submit_anywhere(command)
                remaining = max(0.05, deadline - time.monotonic())
                return deserialize(
                    fut.result(timeout=min(self._retry_s, remaining))
                )
            except (NotLeaderError, *_TIMEOUT_ERRORS, NotaryError) as e:
                if not _retryable_submit_error(e):
                    raise
                if time.monotonic() > deadline:
                    raise
                if ov is not None and not ov.allow_retry("raft.submit", edge):
                    # retry budget exhausted (token bucket per layer+edge,
                    # docs/OVERLOAD.md): under a submit storm, resubmits
                    # must stay a bounded fraction of fresh submits
                    raise NotaryError(
                        "raft submit retry budget exhausted"
                    ) from e
                pause = self._retry_policy.backoff_s(attempt, self._retry_rng)
                attempt += 1
                time.sleep(min(pause, max(0.0, deadline - time.monotonic())))

    def commit(self, states, tx_id, caller_name) -> None:
        result = self._submit_retrying(
            serialize((list(states), tx_id, caller_name))
        )
        if result is not None:
            raise NotaryError(
                f"input states of {tx_id} already consumed", result
            )

    def commit_batch(self, requests):
        """N requests, ONE consensus round: the whole batch travels as one
        log entry and settles in one state-machine apply (r2 VERDICT weak
        #4 — the base-class loop was one full Raft round per transaction;
        reference comparison: DistributedImmutableMap.putAll batches per
        tx, this batches per notary window)."""
        return self.commit_batch_async(requests).collect()

    def commit_batch_async(self, requests):
        """Put the window's consensus round IN FLIGHT and return. The log
        entry is appended and replicating while the caller settles other
        windows — consecutive windows' entries pipeline through the same
        AppendEntries stream (the leader batches outstanding entries per
        send), so replication latency overlaps device verification instead
        of serializing after it (r4 VERDICT weak #2: the cluster notary
        at 4.7k tx/s vs 10.6k single-service was exactly this stall).
        ``collect()`` falls back to the retrying sync path on leader
        change — safe, the state machine is idempotent per tx_id."""
        from .uniqueness import PendingCommit

        if not requests:
            return PendingCommit([])
        command = serialize(
            ("batch", [(list(s), t, c) for (s, t, c) in requests])
        )
        try:
            fut = self.node.submit_anywhere(command)
        except NotLeaderError:
            fut = None
        provider = self

        class _PendingRaftCommit:
            def collect(_self):
                if fut is not None:
                    try:
                        return list(deserialize(
                            fut.result(timeout=provider._retry_s)
                        ))
                    except (NotLeaderError, *_TIMEOUT_ERRORS, NotaryError) as e:
                        if not _retryable_submit_error(e):
                            raise
                return list(provider._submit_retrying(command))

        return _PendingRaftCommit()

    @staticmethod
    def _state_machine_parts(storage_path: str | None):
        """(storage, apply_fn, install_fn) for one replica: durable when a
        storage path is given, else the in-memory map with a snapshot-
        install hook (a durable peer compacted past this replica's log
        replaces the map wholesale). Shared by every construction path —
        co-located clusters and node-embedded replicas must run identical
        state-machine wiring."""
        if storage_path is not None:
            storage = RaftStorage(storage_path)
            return (
                storage,
                RaftUniquenessProvider.storage_state_machine(storage),
                None,
            )
        apply_fn, base = RaftUniquenessProvider.state_machine()

        def install_fn(rows, _last_idx, _last_term, base=base):
            from corda_tpu.crypto import SecureHash

            from .uniqueness import ConsumedStateDetails

            with base._lock:
                base._map = {
                    bytes(k): ConsumedStateDetails(
                        SecureHash(bytes(t)), i, c
                    )
                    for (k, t, i, c) in rows
                }

        return None, apply_fn, install_fn

    @staticmethod
    def make_node(
        name: str, names: list[str], network, storage_dir: str | None = None,
        compact_every: int = 512,
    ) -> "RaftUniquenessProvider":
        """Build (or REBUILD after a crash — state restores from storage)
        one replica."""
        storage, apply_fn, install_fn = (
            RaftUniquenessProvider._state_machine_parts(
                f"{storage_dir}/{name}.db" if storage_dir else None
            )
        )
        node = RaftNode(
            name, list(names), network.create_node(name), apply_fn,
            storage=storage, compact_every=compact_every,
            install_map_fn=install_fn,
        )
        return RaftUniquenessProvider(node)

    def close(self) -> None:
        self.node.stop()

    @staticmethod
    def make_node_on_endpoint(
        name: str, names: list[str], endpoint,
        storage_path: str | None = None, compact_every: int = 512,
        election_timeout_s: tuple[float, float] = (1.0, 2.0),
        heartbeat_s: float = 0.25,
    ) -> "RaftUniquenessProvider":
        """One replica sharing an EXISTING messaging endpoint — the
        multi-process cluster shape, each replica inside its own node
        process talking ``raft.*`` topics over the node fabric (the
        reference runs its Copycat cluster out-of-process over dedicated
        ports, NodeConfiguration.kt:45). Raft traffic coexists with
        session traffic because topics are dispatched independently.
        Default timings are scaled for the polled file broker's ~0.5 s
        worst-case delivery (failover ≈ one election cycle ≈ 2-3 s);
        co-located in-memory clusters keep ``make_cluster``'s fast
        timings. The caller owns start/stop (``provider.node.start()`` /
        ``provider.close()``)."""
        storage, apply_fn, install_fn = (
            RaftUniquenessProvider._state_machine_parts(storage_path)
        )
        node = RaftNode(
            name, list(names), endpoint, apply_fn,
            election_timeout_s=election_timeout_s, heartbeat_s=heartbeat_s,
            storage=storage, compact_every=compact_every,
            install_map_fn=install_fn,
        )
        provider = RaftUniquenessProvider(node)
        # the submit retry window must ride out one full (slowed-down)
        # election cycle, or a mid-failover commit would surface as a
        # notary error instead of completing on the new leader
        provider._retry_s = max(2.0, 3.0 * election_timeout_s[1])
        return provider

    @staticmethod
    def make_cluster(
        names: list[str], network, storage_dir: str | None = None,
        compact_every: int = 512,
    ) -> "list[RaftUniquenessProvider]":
        """Co-located cluster over an InMemoryMessagingNetwork (the
        reference's cluster-of-3-in-one-JVM driver test shape)."""
        providers = [
            RaftUniquenessProvider.make_node(
                name, names, network, storage_dir, compact_every
            )
            for name in names
        ]
        for p in providers:
            p.node.start()
        return providers
