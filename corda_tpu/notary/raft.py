"""Raft-replicated uniqueness provider (CFT notary cluster).

Role parity with the reference's Copycat-based tier
(node/.../services/transactions/RaftUniquenessProvider.kt:4-17 +
DistributedImmutableMap.kt — a replicated put-all-or-report-conflicts map
of consumed states; RaftValidatingNotaryService / RaftNonValidatingNotary-
Service wrap it). Re-implemented from the Raft paper over this framework's
messaging layer (leader election with randomized timeouts, log replication,
majority commit, state-machine apply), because the JVM dependency is the
engine the reference outsources — here it's a first-class component.

The replicated state machine is the uniqueness map: a committed log entry
is a (states, tx_id, caller) commit request; apply() settles it against
the local map, deterministically identical on every replica.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from concurrent.futures import Future

from corda_tpu.messaging import auto_ack
from corda_tpu.serialization import deserialize, serialize

from .uniqueness import (
    InMemoryUniquenessProvider,
    NotaryError,
    UniquenessProvider,
)

T_VOTE = "raft.vote"
T_VOTE_REPLY = "raft.vote-reply"
T_APPEND = "raft.append"
T_APPEND_REPLY = "raft.append-reply"
T_SUBMIT = "raft.submit"
T_SUBMIT_REPLY = "raft.submit-reply"


@dataclasses.dataclass(frozen=True)
class LogEntry:
    term: int
    command: bytes  # serialized (states, tx_id, caller)


class NotLeaderError(Exception):
    def __init__(self, leader: str | None):
        self.leader = leader
        super().__init__(f"not leader; known leader: {leader}")


class RaftNode:
    """One Raft replica. ``apply_fn(command_bytes) -> result_bytes`` is the
    deterministic state machine."""

    FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

    def __init__(
        self, name: str, peers: list[str], messaging, apply_fn,
        election_timeout_s: tuple[float, float] = (0.15, 0.3),
        heartbeat_s: float = 0.05,
        rng: random.Random | None = None,
    ):
        self.name = name
        self.peers = [p for p in peers if p != name]
        self._messaging = messaging
        self._apply_fn = apply_fn
        self._timeout_range = election_timeout_s
        self._heartbeat_s = heartbeat_s
        self._rng = rng or random.Random(name)

        self._lock = threading.RLock()
        self.role = RaftNode.FOLLOWER
        self.current_term = 0
        self.voted_for: str | None = None
        self.log: list[LogEntry] = []
        self.commit_index = -1
        self.last_applied = -1
        self.leader: str | None = None
        # leader volatile state
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}
        self._votes: set[str] = set()
        # client futures waiting on an index we proposed; the entry object
        # is kept alongside so a truncate-and-replace at the same index
        # after a leadership change fails the waiter instead of handing it
        # another command's result
        self._waiters: dict[int, tuple[LogEntry, Future]] = {}
        # remote submissions we're waiting on, by correlation id
        self._pending_remote: dict[str, Future] = {}
        self._corr = 0

        self._deadline = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        for topic, handler in (
            (T_VOTE, self._on_vote), (T_VOTE_REPLY, self._on_vote_reply),
            (T_APPEND, self._on_append), (T_APPEND_REPLY, self._on_append_reply),
            (T_SUBMIT, self._on_submit),
            (T_SUBMIT_REPLY, self._on_submit_reply),
        ):
            messaging.add_handler(topic, auto_ack(handler))

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._reset_timer()
        self._thread = threading.Thread(
            target=self._tick_loop, daemon=True, name=f"raft-{self.name}"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _reset_timer(self) -> None:
        self._deadline = time.monotonic() + self._rng.uniform(*self._timeout_range)

    def _tick_loop(self) -> None:
        while not self._stop.wait(0.01):
            with self._lock:
                now = time.monotonic()
                if self.role == RaftNode.LEADER:
                    if now >= self._deadline:
                        self._deadline = now + self._heartbeat_s
                        self._broadcast_append()
                elif now >= self._deadline:
                    self._start_election()

    # ------------------------------------------------------------ election

    def _start_election(self) -> None:
        self.role = RaftNode.CANDIDATE
        self.current_term += 1
        self.voted_for = self.name
        self._votes = {self.name}
        self.leader = None
        self._reset_timer()
        last_idx = len(self.log) - 1
        last_term = self.log[last_idx].term if last_idx >= 0 else 0
        req = {"term": self.current_term, "candidate": self.name,
               "last_log_index": last_idx, "last_log_term": last_term}
        for p in self.peers:
            self._messaging.send(p, T_VOTE, serialize(req))
        self._maybe_win()  # single-node cluster wins immediately

    def _on_vote(self, msg) -> None:
        req = deserialize(msg.payload)
        with self._lock:
            self._observe_term(req["term"])
            grant = False
            if req["term"] >= self.current_term and self.voted_for in (None, req["candidate"]):
                last_idx = len(self.log) - 1
                last_term = self.log[last_idx].term if last_idx >= 0 else 0
                up_to_date = (req["last_log_term"], req["last_log_index"]) >= (
                    last_term, last_idx,
                )
                if up_to_date:
                    grant = True
                    self.voted_for = req["candidate"]
                    self._reset_timer()
            self._messaging.send(
                msg.sender, T_VOTE_REPLY,
                serialize({"term": self.current_term, "granted": grant,
                           "voter": self.name}),
            )

    def _on_vote_reply(self, msg) -> None:
        rep = deserialize(msg.payload)
        with self._lock:
            self._observe_term(rep["term"])
            if self.role != RaftNode.CANDIDATE or rep["term"] != self.current_term:
                return
            if rep["granted"]:
                self._votes.add(rep["voter"])
                self._maybe_win()

    def _maybe_win(self) -> None:
        if self.role == RaftNode.CANDIDATE and len(self._votes) * 2 > len(self.peers) + 1:
            self.role = RaftNode.LEADER
            self.leader = self.name
            n = len(self.log)
            self._next_index = {p: n for p in self.peers}
            self._match_index = {p: -1 for p in self.peers}
            self._deadline = 0.0  # heartbeat immediately
            self._broadcast_append()

    def _observe_term(self, term: int) -> None:
        if term > self.current_term:
            self.current_term = term
            self.role = RaftNode.FOLLOWER
            self.voted_for = None
            self._votes = set()

    # ------------------------------------------------------------ replication

    def _broadcast_append(self) -> None:
        for p in self.peers:
            self._send_append(p)

    def _send_append(self, peer: str) -> None:
        nxt = self._next_index.get(peer, len(self.log))
        prev_idx = nxt - 1
        prev_term = self.log[prev_idx].term if prev_idx >= 0 else 0
        entries = [(e.term, e.command) for e in self.log[nxt:]]
        req = {
            "term": self.current_term, "leader": self.name,
            "prev_log_index": prev_idx, "prev_log_term": prev_term,
            "entries": entries, "leader_commit": self.commit_index,
        }
        self._messaging.send(peer, T_APPEND, serialize(req))

    def _on_append(self, msg) -> None:
        req = deserialize(msg.payload)
        with self._lock:
            self._observe_term(req["term"])
            ok = False
            match_index = -1
            if req["term"] == self.current_term:
                self.role = RaftNode.FOLLOWER
                self.leader = req["leader"]
                self._reset_timer()
                prev_idx = req["prev_log_index"]
                prev_ok = prev_idx < 0 or (
                    prev_idx < len(self.log)
                    and self.log[prev_idx].term == req["prev_log_term"]
                )
                if prev_ok:
                    ok = True
                    idx = prev_idx + 1
                    for term, cmd in req["entries"]:
                        if idx < len(self.log) and self.log[idx].term != term:
                            del self.log[idx:]
                            self._fail_waiters_from(idx)
                        if idx >= len(self.log):
                            self.log.append(LogEntry(term, cmd))
                        idx += 1
                    match_index = prev_idx + len(req["entries"])
                    if req["leader_commit"] > self.commit_index:
                        self.commit_index = min(
                            req["leader_commit"], len(self.log) - 1
                        )
                        self._apply_committed()
            self._messaging.send(
                msg.sender, T_APPEND_REPLY,
                serialize({"term": self.current_term, "ok": ok,
                           "follower": self.name, "match_index": match_index}),
            )

    def _on_append_reply(self, msg) -> None:
        rep = deserialize(msg.payload)
        with self._lock:
            self._observe_term(rep["term"])
            if self.role != RaftNode.LEADER or rep["term"] != self.current_term:
                return
            p = rep["follower"]
            if rep["ok"]:
                self._match_index[p] = max(self._match_index.get(p, -1),
                                           rep["match_index"])
                self._next_index[p] = self._match_index[p] + 1
                self._advance_commit()
            else:
                self._next_index[p] = max(0, self._next_index.get(p, 1) - 1)
                self._send_append(p)

    def _advance_commit(self) -> None:
        n = len(self.peers) + 1
        for idx in range(len(self.log) - 1, self.commit_index, -1):
            if self.log[idx].term != self.current_term:
                continue
            votes = 1 + sum(1 for p in self.peers if self._match_index.get(p, -1) >= idx)
            if votes * 2 > n:
                self.commit_index = idx
                self._apply_committed()
                break

    def _fail_waiters_from(self, idx: int) -> None:
        """A truncation invalidated every proposal at >= idx."""
        for i in [i for i in self._waiters if i >= idx]:
            _entry, fut = self._waiters.pop(i)
            if not fut.done():
                fut.set_exception(NotLeaderError(self.leader))

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self.log[self.last_applied]
            result = self._apply_fn(entry.command)
            waiter = self._waiters.pop(self.last_applied, None)
            if waiter is not None:
                proposed, fut = waiter
                if fut.done():
                    pass
                elif proposed is entry:
                    fut.set_result(result)
                else:  # a different command landed at our index
                    fut.set_exception(NotLeaderError(self.leader))

    # ------------------------------------------------------------ client API

    def submit(self, command: bytes) -> Future:
        """Leader-only: append + replicate; future completes with the
        state-machine apply result once committed."""
        with self._lock:
            if self.role != RaftNode.LEADER:
                raise NotLeaderError(self.leader)
            entry = LogEntry(self.current_term, command)
            self.log.append(entry)
            idx = len(self.log) - 1
            fut: Future = Future()
            self._waiters[idx] = (entry, fut)
            if not self.peers:  # single-node cluster commits immediately
                self.commit_index = idx
                self._apply_committed()
            else:
                self._broadcast_append()
            return fut

    def _on_submit(self, msg) -> None:
        """Remote client submission (any replica accepts; forwards result
        or redirect)."""
        req = deserialize(msg.payload)
        with self._lock:
            is_leader = self.role == RaftNode.LEADER
            leader = self.leader
        if not is_leader:
            self._messaging.send(
                msg.sender, T_SUBMIT_REPLY,
                serialize({"corr": req["corr"], "redirect": leader}),
            )
            return
        fut = self.submit(req["command"])

        def done(f, corr=req["corr"], sender=msg.sender):
            try:
                self._messaging.send(
                    sender, T_SUBMIT_REPLY,
                    serialize({"corr": corr, "result": f.result()}),
                )
            except Exception as e:
                self._messaging.send(
                    sender, T_SUBMIT_REPLY,
                    serialize({"corr": corr, "error": str(e)}),
                )

        fut.add_done_callback(done)

    def _on_submit_reply(self, msg) -> None:
        rep = deserialize(msg.payload)
        with self._lock:
            fut = self._pending_remote.pop(rep["corr"], None)
        if fut is None or fut.done():
            return
        if "result" in rep:
            fut.set_result(rep["result"])
        elif "redirect" in rep:
            fut.set_exception(NotLeaderError(rep["redirect"]))
        else:
            fut.set_exception(NotaryError(rep.get("error", "submit failed")))

    def submit_anywhere(self, command: bytes) -> Future:
        """Submit locally when leader, else forward to the known leader (or
        probe a peer) over messaging — the CopycatClient role."""
        with self._lock:
            if self.role == RaftNode.LEADER:
                return self.submit(command)
            target = self.leader
            if target is None and self.peers:
                target = self.peers[self._corr % len(self.peers)]
            self._corr += 1
            corr = f"{self.name}-{self._corr}"
            fut: Future = Future()
            self._pending_remote[corr] = fut
        if target is None:
            fut.set_exception(NotLeaderError(None))
            return fut
        self._messaging.send(
            target, T_SUBMIT, serialize({"corr": corr, "command": command})
        )
        return fut


class RaftUniquenessProvider(UniquenessProvider):
    """UniquenessProvider face over a RaftNode whose state machine is a
    local uniqueness map (reference: RaftUniquenessProvider +
    DistributedImmutableMap). Use ``RaftUniquenessProvider.make_cluster``
    to build co-located replicas for tests/demos."""

    def __init__(self, node: RaftNode):
        self.node = node
        # retry window covers one election cycle
        self._retry_s = 2.0

    @staticmethod
    def state_machine(base: UniquenessProvider | None = None):
        base = base or InMemoryUniquenessProvider()

        def apply(command: bytes) -> bytes:
            states, tx_id, caller = deserialize(command)
            try:
                base.commit(states, tx_id, caller)
                return serialize(None)
            except NotaryError as e:
                return serialize(e.conflict)

        return apply, base

    def commit(self, states, tx_id, caller_name) -> None:
        command = serialize((list(states), tx_id, caller_name))
        deadline = time.monotonic() + self._retry_s
        while True:
            try:
                fut = self.node.submit_anywhere(command)
                result = deserialize(fut.result(timeout=self._retry_s))
                break
            except (NotLeaderError, TimeoutError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.02)
        if result is not None:
            raise NotaryError(
                f"input states of {tx_id} already consumed", result
            )

    @staticmethod
    def make_cluster(names: list[str], network) -> "list[RaftUniquenessProvider]":
        """Co-located cluster over an InMemoryMessagingNetwork (the
        reference's cluster-of-3-in-one-JVM driver test shape)."""
        providers = []
        for name in names:
            apply_fn, _base = RaftUniquenessProvider.state_machine()
            node = RaftNode(name, list(names), network.create_node(name), apply_fn)
            providers.append(RaftUniquenessProvider(node))
        for p in providers:
            p.node.start()
        return providers
