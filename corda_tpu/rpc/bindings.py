"""Reactive data-binding over RPC feeds — the client/jfx re-target.

The reference ships a JavaFX data-binding library
(client/jfx/src/main/kotlin/net/corda/client/jfx/): observable-list
combinators (``MappedList.kt``, ``ConcatenatedList.kt``,
``AggregatedList.kt``, ``AssociatedList.kt``, ``FlattenedList.kt``,
``ChosenList.kt``, ``MapValuesList.kt``, ``LeftOuterJoinedMap.kt``,
``ReplayedList.kt``), rx→FX bridges (``ObservableFold.kt``), amount
aggregation (``AmountBindings.kt``), and the model tier that wires a
node's RPC feeds into those collections (``model/NodeMonitorModel.kt``,
``model/ContractStateModel.kt``). The CAPABILITY is composing live node
feeds into derived, incrementally-updated UI state; the JavaFX widget
toolkit itself is the GUI host, which this framework re-targets to the
browser explorer / terminal shells.

This module provides that capability GUI-free:

- ``ObservableValue`` / ``ObservableList`` / ``ObservableMap`` — plain
  thread-safe observables with granular change events.
- Combinators mirroring the jfx-utils set: ``map``, ``filtered`` (with a
  dynamic ``ObservableValue`` predicate), ``sorted``, ``concat``,
  ``flatten_values``, ``aggregated``, ``associated_by``,
  ``left_outer_join``, ``values_list``, ``ChosenList``, ``replayed``.
- ``fold_feed`` / ``accumulate_feed`` — the rx→observable bridge
  (``ObservableFold.kt``): an ``rpc.client.Observable`` feed folds into
  an ``ObservableValue`` or accumulates into an ``ObservableList``.
- ``sum_amounts`` — ``AmountBindings.kt``'s token-filtered quantity sum
  as a live value.
- ``NodeMonitorModel`` — wires one RPC proxy's vault / transaction /
  network-map feeds into observable collections
  (``model/NodeMonitorModel.kt:31-61``'s role).

Change events are coarse-typed (add/remove/update/reset) and delivered
synchronously on the mutating thread; derived views update their backing
store incrementally (``sorted`` re-inserts by bisection; ``aggregated``
rebuilds only the touched group).
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
from typing import Any, Callable


@dataclasses.dataclass(frozen=True)
class Change:
    """One granular collection change ('reset' carries the new snapshot)."""

    kind: str              # add | remove | update | reset
    index: int = -1
    element: Any = None
    old_element: Any = None


class _Observable:
    def __init__(self):
        self._listeners: list[Callable] = []
        self._lock = threading.RLock()

    def on_change(self, listener: Callable) -> Callable:
        """Register; returns the listener for unhook bookkeeping."""
        with self._lock:
            self._listeners.append(listener)
        return listener

    def remove_listener(self, listener: Callable) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def _emit(self, event) -> None:
        # Mutators call this while STILL HOLDING self._lock (an RLock, so
        # the re-acquire here is free): releasing before emitting let two
        # concurrent mutators deliver index-carrying Change events out of
        # order, silently diverging every derived view (map/filtered/
        # replayed mirror the source by ch.index). Listener code therefore
        # runs under the source's lock — listeners are synchronous and
        # must not block on other threads' mutations of the same source.
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            fn(event)


class ObservableValue(_Observable):
    """A current value + change notifications (reference:
    ObservableValue/SimpleObjectProperty as used across client/jfx)."""

    def __init__(self, value=None):
        super().__init__()
        self._value = value

    def get(self):
        with self._lock:
            return self._value

    def set(self, value) -> None:
        with self._lock:
            old = self._value
            self._value = value
            if old != value:
                self._emit((old, value))

    def map(self, fn: Callable) -> "ObservableValue":
        """Derived value (reference: EasyBind.map / ObservableUtilities)."""
        out = ObservableValue(fn(self.get()))
        self.on_change(lambda ch: out.set(fn(ch[1])))
        return out

    @staticmethod
    def combine(fn: Callable, *sources: "ObservableValue") -> "ObservableValue":
        """fn over several live values, recomputed on any change."""
        out = ObservableValue(fn(*(s.get() for s in sources)))

        def recompute(_ch):
            out.set(fn(*(s.get() for s in sources)))

        for s in sources:
            s.on_change(recompute)
        return out


class ObservableList(_Observable):
    """A list with granular change events; every combinator returns a new
    live-updating ObservableList (the jfx-utils composition style)."""

    def __init__(self, initial=()):
        super().__init__()
        self._items: list = list(initial)

    # ------------------------------------------------------------ mutation
    def append(self, element) -> None:
        with self._lock:
            self._items.append(element)
            idx = len(self._items) - 1
            self._emit(Change("add", idx, element))

    def insert(self, index: int, element) -> None:
        with self._lock:
            self._items.insert(index, element)
            self._emit(Change("add", index, element))

    def remove_at(self, index: int):
        with self._lock:
            element = self._items.pop(index)
            self._emit(Change("remove", index, element))
        return element

    def remove(self, element) -> bool:
        with self._lock:
            try:
                idx = self._items.index(element)
            except ValueError:
                return False
            self._items.pop(idx)
            self._emit(Change("remove", idx, element))
        return True

    def update_at(self, index: int, element) -> None:
        with self._lock:
            old = self._items[index]
            self._items[index] = element
            self._emit(Change("update", index, element, old))

    def reset(self, items) -> None:
        with self._lock:
            self._items = list(items)
            snap = list(self._items)
            self._emit(Change("reset", element=snap))

    # ------------------------------------------------------------- reading
    def snapshot(self) -> list:
        with self._lock:
            return list(self._items)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def __iter__(self):
        return iter(self.snapshot())

    def __getitem__(self, i):
        with self._lock:
            return self._items[i]

    # --------------------------------------------------------- combinators
    def map(self, fn: Callable) -> "ObservableList":
        """reference: MappedList.kt — element-wise transform, updated
        per-change (no full recompute)."""
        out = ObservableList(fn(x) for x in self.snapshot())

        def on_change(ch: Change):
            if ch.kind == "add":
                out.insert(ch.index, fn(ch.element))
            elif ch.kind == "remove":
                out.remove_at(ch.index)
            elif ch.kind == "update":
                out.update_at(ch.index, fn(ch.element))
            else:
                out.reset(fn(x) for x in ch.element)

        self.on_change(on_change)
        return out

    def filtered(self, predicate) -> "ObservableList":
        """reference: FilteredList as used by ChosenList consumers; the
        predicate may be a plain callable or an ObservableValue holding
        one (dynamic re-filter on predicate change). Granular source
        changes update incrementally (an ``included`` mask maps source
        indices to output indices); only a predicate change rebuilds."""
        dynamic = isinstance(predicate, ObservableValue)

        def pred():
            return predicate.get() if dynamic else predicate

        included = [pred()(x) for x in self.snapshot()]
        out = ObservableList(
            x for x, ok in zip(self.snapshot(), included) if ok
        )

        def out_index(src_idx: int) -> int:
            return sum(1 for ok in included[:src_idx] if ok)

        def on_change(ch: Change):
            if ch.kind == "add":
                ok = pred()(ch.element)
                included.insert(ch.index, ok)
                if ok:
                    out.insert(out_index(ch.index), ch.element)
            elif ch.kind == "remove":
                was = included.pop(ch.index)
                if was:
                    out.remove_at(out_index(ch.index))
            elif ch.kind == "update":
                was = included[ch.index]
                now = pred()(ch.element)
                pos = out_index(ch.index)
                included[ch.index] = now
                if was and now:
                    out.update_at(pos, ch.element)
                elif was:
                    out.remove_at(pos)
                elif now:
                    out.insert(pos, ch.element)
            else:
                included[:] = [pred()(x) for x in ch.element]
                out.reset(
                    x for x, ok in zip(ch.element, included) if ok
                )

        self.on_change(on_change)
        if dynamic:
            def re_filter(_ch):
                included[:] = [pred()(x) for x in self.snapshot()]
                out.reset(
                    x for x, ok in zip(self.snapshot(), included) if ok
                )

            predicate.on_change(re_filter)
        return out

    def sorted(self, key: Callable = lambda x: x) -> "ObservableList":
        """reference: SortedList role — bisection insert per add."""
        out = ObservableList(sorted(self.snapshot(), key=key))

        def on_change(ch: Change):
            if ch.kind == "add":
                keys = [key(x) for x in out.snapshot()]
                out.insert(bisect.bisect_right(keys, key(ch.element)),
                           ch.element)
            elif ch.kind == "remove":
                out.remove(ch.element)
            elif ch.kind == "update":
                out.remove(ch.old_element)
                keys = [key(x) for x in out.snapshot()]
                out.insert(bisect.bisect_right(keys, key(ch.element)),
                           ch.element)
            else:
                out.reset(sorted(ch.element, key=key))

        self.on_change(on_change)
        return out

    def aggregated(self, group_key: Callable,
                   assemble: Callable) -> "ObservableList":
        """reference: AggregatedList.kt — one assembled row per distinct
        group key; only the touched group rebuilds on change."""
        out = ObservableList()
        groups: dict = {}

        def rebuild_group(k):
            members = [x for x in self.snapshot() if group_key(x) == k]
            row = assemble(k, members) if members else None
            if k in groups:
                idx = list(groups).index(k)  # rows mirror key order
                if row is None:
                    del groups[k]
                    out.remove_at(idx)
                else:
                    groups[k] = row
                    out.update_at(idx, row)
            elif row is not None:
                groups[k] = row
                out.append(row)

        def on_change(ch: Change):
            if ch.kind in ("add", "remove"):
                rebuild_group(group_key(ch.element))
            elif ch.kind == "update":
                for k in {group_key(ch.old_element), group_key(ch.element)}:
                    rebuild_group(k)
            else:
                groups.clear()
                rows = []
                for x in ch.element:
                    k = group_key(x)
                    if k not in groups:
                        members = [y for y in ch.element
                                   if group_key(y) == k]
                        groups[k] = assemble(k, members)
                        rows.append(groups[k])
                out.reset(rows)

        on_change(Change("reset", element=self.snapshot()))
        self.on_change(on_change)
        return out

    def associated_by(self, key: Callable) -> "ObservableMap":
        """reference: AssociatedList.kt — live key→element map (last
        writer wins per key, as the reference's unique-key contract)."""
        out = ObservableMap({key(x): x for x in self.snapshot()})

        def on_change(ch: Change):
            if ch.kind == "add" or ch.kind == "update":
                if ch.kind == "update":
                    old_k = key(ch.old_element)
                    if old_k != key(ch.element):
                        out.discard(old_k)
                out.put(key(ch.element), ch.element)
            elif ch.kind == "remove":
                out.discard(key(ch.element))
            else:
                out.reset({key(x): x for x in ch.element})

        self.on_change(on_change)
        return out

    def replayed(self) -> "ObservableList":
        """reference: ReplayedList.kt — a decoupled copy whose listeners
        observe a stable snapshot-consistent view (thread-hop isolation
        without the FX thread)."""
        out = ObservableList(self.snapshot())

        def on_change(ch: Change):
            if ch.kind == "add":
                out.insert(ch.index, ch.element)
            elif ch.kind == "remove":
                out.remove_at(ch.index)
            elif ch.kind == "update":
                out.update_at(ch.index, ch.element)
            else:
                out.reset(ch.element)

        self.on_change(on_change)
        return out


def concat(lists: list[ObservableList]) -> ObservableList:
    """reference: ConcatenatedList.kt — a live concatenation view."""
    out = ObservableList(x for lst in lists for x in lst.snapshot())

    def rebuild(_ch=None):
        out.reset(x for lst in lists for x in lst.snapshot())

    for lst in lists:
        lst.on_change(rebuild)
    return out


def flatten_values(values: list[ObservableValue]) -> ObservableList:
    """reference: FlattenedList.kt — ObservableValues presented as a live
    list of their current contents."""
    out = ObservableList(v.get() for v in values)
    for i, v in enumerate(values):
        v.on_change(lambda ch, i=i: out.update_at(i, ch[1]))
    return out


class ObservableMap(_Observable):
    """Key→value with put/discard events (reference:
    ReadOnlyBackedObservableMapBase.kt roles)."""

    def __init__(self, initial: dict | None = None):
        super().__init__()
        self._map: dict = dict(initial or {})

    def get(self, k, default=None):
        with self._lock:
            return self._map.get(k, default)

    def put(self, k, v) -> None:
        with self._lock:
            self._map[k] = v
            self._emit(("put", k, v))

    def discard(self, k) -> None:
        with self._lock:
            if k not in self._map:
                return
            v = self._map.pop(k)
            self._emit(("discard", k, v))

    def reset(self, mapping: dict) -> None:
        with self._lock:
            self._map = dict(mapping)
            snap = dict(self._map)
            self._emit(("reset", None, snap))

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._map)

    def values_list(self) -> ObservableList:
        """reference: MapValuesList.kt — live list of the map's values."""
        out = ObservableList(self.snapshot().values())
        self.on_change(lambda _e: out.reset(self.snapshot().values()))
        return out

    def left_outer_join(self, right: "ObservableMap",
                        join: Callable) -> "ObservableMap":
        """reference: LeftOuterJoinedMap.kt — every left key mapped to
        join(left_value, right_value_or_None), live on both sides."""
        def build():
            rs = right.snapshot()
            return {
                k: join(v, rs.get(k)) for k, v in self.snapshot().items()
            }

        out = ObservableMap(build())
        self.on_change(lambda _e: out.reset(build()))
        right.on_change(lambda _e: out.reset(build()))
        return out


class ChosenList(ObservableList):
    """reference: ChosenList.kt — presents whichever ObservableList an
    ObservableValue currently holds, re-wiring on choice change."""

    def __init__(self, chosen: ObservableValue):
        current = chosen.get()
        super().__init__(current.snapshot() if current else ())
        self._hook = None
        self._wire(current)
        chosen.on_change(lambda ch: self._rewire(ch[0], ch[1]))

    def _wire(self, source: ObservableList | None):
        if source is None:
            return

        def on_change(ch: Change):
            if ch.kind == "add":
                self.insert(ch.index, ch.element)
            elif ch.kind == "remove":
                self.remove_at(ch.index)
            elif ch.kind == "update":
                self.update_at(ch.index, ch.element)
            else:
                self.reset(ch.element)

        self._hook = (source, source.on_change(on_change))

    def _rewire(self, _old, new: ObservableList | None):
        if self._hook is not None:
            src, fn = self._hook
            src.remove_listener(fn)
            self._hook = None
        self._wire(new)
        self.reset(new.snapshot() if new else ())


# ------------------------------------------------------- rx→binding bridge

def fold_feed(feed, initial, folder: Callable) -> ObservableValue:
    """reference: ObservableFold.kt foldToObservableValue — an
    ``rpc.client.Observable`` (snapshot + pushed updates) folded into a
    live value. A LIST/TUPLE snapshot seeds the fold element-wise; a
    non-sequence snapshot (e.g. the vault's Page) is NOT update-shaped
    and is left to the caller to seed explicitly."""
    out = ObservableValue(initial)
    state = {"acc": initial}
    lock = threading.Lock()

    def on_update(update):
        with lock:
            state["acc"] = folder(state["acc"], update)
            out.set(state["acc"])

    snap = getattr(feed, "snapshot", None)
    if isinstance(snap, (list, tuple)):
        for item in snap:
            on_update(item)
    feed.subscribe(on_update)
    return out


def accumulate_feed(
    feed, extract: Callable = lambda u: [u], seed=(),
) -> ObservableList:
    """reference: ObservableFold.kt foldToObservableList — feed updates
    appended into a live list (``extract`` maps one update to zero or
    more elements, e.g. produced states out of a vault update). Snapshot
    seeding follows ``fold_feed``'s rule: only sequence snapshots are
    update-shaped; non-update-shaped snapshot elements (a vault Page's
    pre-existing states) go in via ``seed``. All seeding happens BEFORE
    the subscription so updates pushed during construction can neither
    land ahead of the snapshot nor duplicate into it — the reference's
    snapshot-then-updates ordering."""
    out = ObservableList()

    def on_update(update):
        for el in extract(update):
            out.append(el)

    for el in seed:
        out.append(el)
    snap = getattr(feed, "snapshot", None)
    if isinstance(snap, (list, tuple)):
        for item in snap:
            on_update(item)
    feed.subscribe(on_update)
    return out


def sum_amounts(states: ObservableList, token) -> ObservableValue:
    """reference: AmountBindings.kt — live sum of Amount quantities for
    one token over an observable list of amounts."""
    def total():
        return sum(
            a.quantity for a in states.snapshot() if a.token == token
        )

    out = ObservableValue(total())
    states.on_change(lambda _ch: out.set(total()))
    return out


class PolledValue(ObservableValue):
    """A pull-refreshed observable: wraps a read callable; ``refresh()``
    re-reads and emits on change. The binding shape for RPC surfaces that
    are snapshots rather than push feeds (metrics, counts) — consumers
    compose it with the usual combinators (``map``, ``combine``) and a
    caller-owned refresh cadence."""

    def __init__(self, read: Callable):
        super().__init__(read())
        self._read = read

    def refresh(self):
        value = self._read()
        self.set(value)
        return value


def serving_metrics_value(proxy) -> PolledValue:
    """Live read binding over the node's serving-scheduler metrics
    (``CordaRPCOps.serving_metrics``): queue depth/rows, wait time, batch
    occupancy/latency, shed + rejected counts — the ``serving`` section
    of the monitoring snapshot as an ObservableValue the explorer/shell
    widgets fold into their views."""
    return PolledValue(lambda: proxy.serving_metrics())


def monitoring_snapshot_value(proxy) -> PolledValue:
    """Read binding over the full sectioned monitoring snapshot
    (``serving`` / ``process`` / ``node``)."""
    return PolledValue(lambda: proxy.monitoring_snapshot())


def profiler_snapshot_value(proxy) -> PolledValue:
    """Read binding over the kernel profiler's accounting
    (``CordaRPCOps.profiler_snapshot``): per-kernel/per-bucket compile vs
    execute split, batch efficiency, and roofline fractions — refresh
    while a profiled run executes to watch the split evolve."""
    return PolledValue(lambda: proxy.profiler_snapshot())


def devicemon_snapshot_value(proxy) -> PolledValue:
    """Read binding over the per-device telemetry registry
    (``CordaRPCOps.devicemon_snapshot``): per-ordinal in-flight depth,
    dispatch/settle counts, rows vs padded lanes, execute EWMA,
    heartbeat age and health flags — refresh to watch a straggler
    develop in the explorer's mesh view."""
    return PolledValue(lambda: proxy.devicemon_snapshot())


def slo_status_value(proxy) -> PolledValue:
    """Read binding over the SLO monitor's evaluated objectives
    (``CordaRPCOps.slo_status``): windowed p99 / error-rate per
    objective with breach flags — the attainment widget's feed."""
    return PolledValue(lambda: proxy.slo_status())


def timeline_snapshot_value(proxy) -> PolledValue:
    """Read binding over the telemetry timeline's ring snapshot
    (``CordaRPCOps.timeline_snapshot``): per-series rings of counter
    deltas, windowed timer quantiles and monitor gauges — the sparkline
    widget's feed; ``tools_timeline.py`` renders it in the terminal."""
    return PolledValue(lambda: proxy.timeline_snapshot())


def flowprof_snapshot_value(proxy) -> PolledValue:
    """Read binding over the critical-path phase-accounting waterfall
    (``CordaRPCOps.flowprof_snapshot``): per-phase p50/p99 and per-class
    phase shares — refresh under load to watch where flow wall is going
    as the knee approaches."""
    return PolledValue(lambda: proxy.flowprof_snapshot())


def contention_snapshot_value(proxy, top_n: int = 16) -> PolledValue:
    """Read binding over the lock-contention observatory's tables
    (``CordaRPCOps.contention_snapshot``): the top-contended table and
    the holder→waiter wait edges — refresh under load to watch a convoy
    form."""
    return PolledValue(lambda: proxy.contention_snapshot(top_n=top_n))


def speedup_ledger_value(proxy) -> PolledValue:
    """Read binding over the causal profiler's speedup ledger
    (``CordaRPCOps.speedup_ledger``): phases ranked by predicted
    knee-qps payoff from the last virtual-speedup run."""
    return PolledValue(lambda: proxy.speedup_ledger())


def metrics_text_value(proxy) -> PolledValue:
    """Read binding over the Prometheus text exposition
    (``CordaRPCOps.metrics_text``) — the scrape body as a live value the
    shell/explorer surfaces render or re-export."""
    return PolledValue(lambda: proxy.metrics_text())


def trace_dump_value(proxy, limit: int = 200) -> PolledValue:
    """Read binding over the tracer's recent-span ring
    (``CordaRPCOps.trace_dump``): each refresh pulls the latest finished
    spans, for live trace-waterfall widgets."""
    return PolledValue(lambda: proxy.trace_dump(limit=limit))


def trace_for_value(proxy, flow_id: str) -> PolledValue:
    """Read binding over one flow's trace (``CordaRPCOps.trace_for``) —
    refresh while the flow runs to watch its spans land."""
    return PolledValue(lambda: proxy.trace_for(flow_id))


def cluster_snapshot_value(proxy) -> PolledValue:
    """Read binding over the federated cluster document
    (``CordaRPCOps.cluster_snapshot``): per-node monitoring snapshots
    plus the mesh rollup — the fleet-overview widget's feed."""
    return PolledValue(lambda: proxy.cluster_snapshot())


# ------------------------------------------------------------- model tier

class NodeMonitorModel:
    """Wire one RPC proxy's feeds into observable collections
    (reference: model/NodeMonitorModel.kt:31-61 — the model every jfx
    screen consumes). Feeds used: ``vault_track`` (produced/consumed
    states), ``validated_transactions_track``, ``network_map_feed``."""

    def __init__(self, proxy):
        vault_feed = proxy.vault_track()
        # the vault feed's snapshot is a Page (not update-shaped):
        # vault_updates carries the pushed Update stream; produced_states
        # is the FLAT live list of states — pre-existing page states
        # seeded BEFORE the subscription (an update pushed while this
        # model is constructed must append after, never ahead of or
        # duplicated with, the snapshot it is already part of)
        page = getattr(vault_feed, "snapshot", None)
        self.vault_updates = accumulate_feed(vault_feed)
        self.produced_states = accumulate_feed(
            vault_feed,
            extract=lambda u: list(getattr(u, "produced", ())),
            seed=list(getattr(page, "states", ()) or ()),
        )
        self.transactions = accumulate_feed(
            proxy.validated_transactions_track()
        )
        self.network_nodes = accumulate_feed(proxy.network_map_feed())
