"""RPC server: exposes CordaRPCOps over the messaging layer.

Capability parity with the reference's ``RPCServer``
(node/.../services/messaging/RPCServer.kt) speaking the RPCApi protocol
(node-api/.../RPCApi.kt:15-50): clients send ``RpcRequest`` to the node's
request topic with a reply topic; replies carry the result or error;
streamed feeds (vault track, network map feed, state machine updates) are
pushed as ``Observation`` messages tagged by subscription id until the
client unsubscribes.

Auth mirrors the reference's rpcUsers model (NodeConfiguration.kt rpcUsers
+ per-method/per-flow permission strings): every request carries
username/password checked against the configured users; flow starts
additionally require ``StartFlow.<class>`` (or ``ALL``).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from concurrent.futures import ThreadPoolExecutor

from corda_tpu.node.config import RpcUser
from corda_tpu.serialization import cbe_serializable, deserialize, serialize

from .ops import CordaRPCOps, PermissionException, start_flow_permission

logger = logging.getLogger(__name__)

# decoy for unknown usernames: runs the same constant-time plaintext
# compare a known dev-mode user would, so unknown-vs-known timing is
# equalized for the plaintext (dev default) case without handing
# unauthenticated callers a pbkdf2 CPU-amplification lever. (For hashed
# entries the pbkdf2 cost itself still differs from the decoy; hashing
# at rest trades that residual username-timing signal for not storing
# secrets in clear.)
_DUMMY_USER = RpcUser("", "\x00corda-tpu-rpc-decoy\x00", ())

RPC_REQUEST_TOPIC = "rpc.request"


@cbe_serializable(name="rpc.Request")
@dataclasses.dataclass(frozen=True)
class RpcRequest:
    request_id: str
    username: str
    password: str
    method: str
    args: tuple = ()
    kwargs_blob: bytes = b""     # CBE dict (kwargs keys are strings)
    reply_to: str = ""           # client node name on the transport


@cbe_serializable(name="rpc.Reply")
@dataclasses.dataclass(frozen=True)
class RpcReply:
    request_id: str
    ok: bool
    payload_blob: bytes = b""    # CBE result when ok
    error: str = ""


@cbe_serializable(name="rpc.Observation")
@dataclasses.dataclass(frozen=True)
class Observation:
    subscription_id: str
    payload_blob: bytes
    completed: bool = False


RPC_REPLY_TOPIC = "rpc.reply"

# methods any authenticated user may call; everything else needs an explicit
# permission or ALL (flow starts use StartFlow.<class>)
_OPEN_METHODS = {
    "ping", "current_node_time", "node_info", "network_map_snapshot",
    "notary_identities", "registered_flows",
}

# feed methods: invoked with a server-side callback bridged to Observations
_FEED_METHODS = {
    "vault_track": "vault_track",
    "network_map_feed": "network_map_feed",
    "validated_transactions_track": "validated_transactions_track",
}


class RPCServer:
    """Dispatches RpcRequests against a CordaRPCOps instance."""

    def __init__(self, ops: CordaRPCOps, messaging, rpc_users=(),
                 max_workers: int = 8):
        self._ops = ops
        self._messaging = messaging
        self._users = {u.username: u for u in rpc_users}
        self._lock = threading.Lock()
        self._subscriptions: dict[str, dict] = {}  # sub id -> {client, push}
        self._counter = 0
        # requests dispatch on a pool, NEVER on the transport's delivery
        # thread: a blocking op (flow_result while the flow still needs
        # messaging) would otherwise deadlock all message delivery
        # (reference: RPCServer's rpc-server thread pool)
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="rpc-server"
        )
        messaging.add_handler(RPC_REQUEST_TOPIC, self._on_request)

    # ------------------------------------------------------------ auth
    def _authenticate(self, req: RpcRequest):
        user = self._users.get(req.username)
        # check_password compares in constant time (and handles pbkdf2$
        # salted-hash at-rest entries); always run it — even for unknown
        # users, against a dummy — so response timing doesn't leak whether
        # a username exists
        candidate = user if user is not None else _DUMMY_USER
        if not candidate.check_password(req.password) or user is None:
            raise PermissionException("invalid RPC credentials")
        return user

    @staticmethod
    def _authorise(user, req: RpcRequest) -> None:
        if req.method in _OPEN_METHODS:
            return
        perms = set(user.permissions)
        if "ALL" in perms:
            return
        if req.method == "start_flow_dynamic":
            needed = start_flow_permission(req.args[0])
            if needed in perms:
                return
            raise PermissionException(
                f"user {user.username} may not start {req.args[0]}"
            )
        if req.method in perms or f"InvokeRpc.{req.method}" in perms:
            return
        raise PermissionException(
            f"user {user.username} may not call {req.method}"
        )

    # ------------------------------------------------------------ dispatch
    def _on_request(self, msg, ack=None) -> None:
        try:
            req = deserialize(msg.payload)
            assert isinstance(req, RpcRequest)
        except Exception:
            logger.exception("malformed RPC request dropped")
            if ack:
                ack()
            return
        self._pool.submit(self._handle, req, ack)

    def _handle(self, req: RpcRequest, ack) -> None:
        try:
            user = self._authenticate(req)
            self._authorise(user, req)
            if req.method in _FEED_METHODS:
                result = self._subscribe_feed(req)
            elif req.method == "unsubscribe":
                result = self._unsubscribe(req.args[0])
            else:
                fn = getattr(self._ops, req.method, None)
                if fn is None or req.method.startswith("_"):
                    raise PermissionException(
                        f"unknown RPC method {req.method}"
                    )
                kwargs = deserialize(req.kwargs_blob) if req.kwargs_blob else {}
                result = fn(*req.args, **kwargs)
            reply = RpcReply(req.request_id, True, serialize(result))
        except Exception as e:
            reply = RpcReply(
                req.request_id, False, b"", f"{type(e).__name__}: {e}"
            )
        self._messaging.send(
            req.reply_to, RPC_REPLY_TOPIC, serialize(reply),
            msg_id=f"rpcreply-{req.request_id}",
        )
        if ack:
            ack()

    # ------------------------------------------------------------- feeds
    def _subscribe_feed(self, req: RpcRequest):
        with self._lock:
            self._counter += 1
            sub_id = f"sub-{self._counter}"
        client = req.reply_to
        seq = {"n": 0}

        def push(*update):
            payload = update[0] if len(update) == 1 else list(update)
            with self._lock:
                if sub_id not in self._subscriptions:
                    return
                seq["n"] += 1
                n = seq["n"]
            try:
                self._messaging.send(
                    client, RPC_REPLY_TOPIC,
                    serialize(Observation(sub_id, serialize(payload))),
                    msg_id=f"obs-{sub_id}-{n}",
                )
            except Exception:
                logger.exception("dropping observation for %s", sub_id)

        with self._lock:
            self._subscriptions[sub_id] = {"client": client, "push": push}
        snapshot = getattr(self._ops, _FEED_METHODS[req.method])(push)
        return {"subscription_id": sub_id, "snapshot": snapshot}

    def _unsubscribe(self, sub_id: str) -> bool:
        with self._lock:
            sub = self._subscriptions.pop(sub_id, None)
        if sub is None:
            return False
        # detach from the underlying feed so long-lived nodes don't
        # accumulate dead callbacks
        self._ops.untrack(sub["push"])
        return True

    def stop(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
