"""JSON (de)serialization for framework types — the client/jackson tier.

Capability parity with the reference's JacksonSupport
(client/jackson/.../JacksonSupport.kt:40-180): a mapper that renders the
platform's core types in human-usable JSON forms and parses them back,
with PARTIES resolved through a pluggable backend — the identity service
in-process, or live RPC for remote clients (RpcObjectMapper /
IdentityObjectMapper / NoPartyObjectMapper roles).

Wire forms (matching the reference serializers' shapes):

- ``SecureHash``     → hex string
- ``PublicKey``      → ``"<scheme_id>:<hex>"``
- ``CordaX500Name``  → X.500 string (``"O=Bank A, L=London, C=GB"``)
- ``Party``          → its X.500 string (deserialized via resolution)
- ``AnonymousParty`` → its key form
- ``Amount``         → ``"<quantity> <product>"`` for plain tokens
                       (AmountDeserializer's string form), structural
                       object for Issued tokens
- ``StateRef``       → ``"<txhash>(<index>)"``
- ``bytes``          → base64
- dataclasses        → ``{field: value}`` objects (+ ``"@type"`` tag for
                       CBE-registered classes, so parsing is type-driven)
"""

from __future__ import annotations

import base64
import dataclasses
import enum
import json
import typing

from corda_tpu.crypto import SecureHash
from corda_tpu.crypto.keys import PublicKey
from corda_tpu.ledger import (
    Amount,
    AnonymousParty,
    CordaX500Name,
    Party,
    StateRef,
)
from corda_tpu.serialization.cbe import _ENCODERS, _REGISTRY


class JsonSerializationError(Exception):
    pass


class JsonMapper:
    """The NoPartyObjectMapper tier: serializes everything, refuses to
    DESERIALIZE parties (no resolution backend)."""

    # ------------------------------------------------------------ writing

    def to_json_value(self, obj):
        if obj is None or isinstance(obj, (bool, int, float, str)):
            return obj
        if isinstance(obj, SecureHash):
            return str(obj)
        if isinstance(obj, PublicKey):
            return f"{obj.scheme_id}:{obj.encoded.hex()}"
        if isinstance(obj, CordaX500Name):
            return str(obj)
        if isinstance(obj, Party):
            return str(obj)
        if isinstance(obj, AnonymousParty):
            # key form (reference: AnonymousPartySerializer writes the key,
            # not the display string — it must parse back)
            return self.to_json_value(obj.owning_key)
        if isinstance(obj, StateRef):
            return str(obj)
        if isinstance(obj, Amount):
            if isinstance(obj.token, str):
                return f"{obj.quantity} {obj.token}"
            return {
                "quantity": obj.quantity,
                "token": self.to_json_value(obj.token),
            }
        if isinstance(obj, (bytes, bytearray)):
            return base64.b64encode(bytes(obj)).decode()
        if isinstance(obj, enum.Enum):
            return obj.value
        if isinstance(obj, dict):
            return {str(k): self.to_json_value(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple, set, frozenset)):
            return [self.to_json_value(x) for x in obj]
        if dataclasses.is_dataclass(obj):
            out = {}
            reg = _ENCODERS.get(type(obj))
            if reg is not None:
                out["@type"] = reg[0]
            for f in dataclasses.fields(obj):
                out[f.name] = self.to_json_value(getattr(obj, f.name))
            return out
        # objects exposing their registered-field form (e.g. CBE customs
        # that are not dataclasses)
        reg = _ENCODERS.get(type(obj))
        if reg is not None:
            name, to_fields = reg
            out = {"@type": name}
            for k, v in to_fields(obj).items():
                out[k] = self.to_json_value(v)
            return out
        raise JsonSerializationError(
            f"no JSON form for {type(obj).__name__}"
        )

    def to_json(self, obj, **kwargs) -> str:
        return json.dumps(self.to_json_value(obj), **kwargs)

    # ------------------------------------------------------------ parties

    def well_known_party_from_x500_name(self, name: CordaX500Name):
        raise JsonSerializationError(
            "this mapper cannot resolve parties — use an identity- or "
            "RPC-backed mapper"
        )

    def party_from_key(self, key: PublicKey):
        raise JsonSerializationError(
            "this mapper cannot resolve parties — use an identity- or "
            "RPC-backed mapper"
        )

    # ------------------------------------------------------------ reading

    def parse(self, value, cls):
        """Parse a JSON value (already json.loads'ed) as ``cls``."""
        origin = typing.get_origin(cls)
        if origin in (list, tuple, set, frozenset):
            args = typing.get_args(cls) or (object,)
            item_cls = args[0]
            seq = [self.parse(v, item_cls) for v in value]
            return origin(seq) if origin is not list else seq
        if origin is dict:
            kt, vt = (typing.get_args(cls) or (str, object))[:2]
            return {
                self.parse(k, kt): self.parse(v, vt)
                for k, v in value.items()
            }
        if origin is typing.Union or str(origin) == "types.UnionType":
            last_err = None
            for alt in typing.get_args(cls):
                if alt is type(None):
                    if value is None:
                        return None
                    continue
                try:
                    return self.parse(value, alt)
                except Exception as e:
                    last_err = e
            raise JsonSerializationError(f"no union arm matched: {last_err}")
        if cls in (object, typing.Any) or cls is None:
            return value
        if cls in (list, tuple, set, frozenset):  # unparameterized
            return cls(value)
        if cls is dict:
            return dict(value)
        if cls is SecureHash:
            return SecureHash.parse(value)
        if cls is PublicKey:
            scheme, _, hexed = value.partition(":")
            return PublicKey(int(scheme), bytes.fromhex(hexed))
        if cls is CordaX500Name:
            return CordaX500Name.parse(value)
        if cls is Party:
            party = self.well_known_party_from_x500_name(
                CordaX500Name.parse(value)
            )
            if party is None:
                raise JsonSerializationError(f"unknown party: {value!r}")
            return party
        if cls is AnonymousParty:
            scheme, _, hexed = value.partition(":")
            return AnonymousParty(PublicKey(int(scheme), bytes.fromhex(hexed)))
        if cls is StateRef:
            head, _, idx = value.rpartition("(")
            return StateRef(SecureHash.parse(head), int(idx.rstrip(")")))
        if cls is Amount:
            if isinstance(value, str):
                qty, _, product = value.partition(" ")
                return Amount(int(qty), product)
            return Amount(
                value["quantity"], self.parse(value["token"], object)
            )
        if cls is bytes:
            return base64.b64decode(value)
        if isinstance(cls, type) and issubclass(cls, enum.Enum):
            return cls(value)
        if cls is int or cls is float or cls is str or cls is bool:
            return cls(value)
        if isinstance(value, dict) and "@type" in value:
            reg = _REGISTRY.get(value["@type"])
            if reg is None:
                raise JsonSerializationError(
                    f"unknown @type {value['@type']!r}"
                )
            reg_cls, from_fields = reg
            fields = {
                k: self._parse_registered_field(reg_cls, k, v)
                for k, v in value.items() if k != "@type"
            }
            return from_fields(fields)
        if (isinstance(cls, type) and dataclasses.is_dataclass(cls)
                and isinstance(value, dict)):
            hints = typing.get_type_hints(cls)
            kwargs = {
                f.name: self.parse(value[f.name], hints.get(f.name, object))
                for f in dataclasses.fields(cls) if f.name in value
            }
            return cls(**kwargs)
        raise JsonSerializationError(
            f"cannot parse {value!r} as {getattr(cls, '__name__', cls)}"
        )

    def _parse_registered_field(self, cls, name, value):
        hints = {}
        if isinstance(cls, type) and dataclasses.is_dataclass(cls):
            try:
                hints = typing.get_type_hints(cls)
            except Exception:
                hints = {}
        return self.parse(value, hints.get(name, object))

    def from_json(self, text: str, cls):
        return self.parse(json.loads(text), cls)


class IdentityJsonMapper(JsonMapper):
    """Party resolution via an in-process IdentityService (reference:
    IdentityObjectMapper)."""

    def __init__(self, identity_service):
        self._identities = identity_service

    def well_known_party_from_x500_name(self, name):
        return self._identities.party_from_name(name)

    def party_from_key(self, key):
        return self._identities.party_from_key(key)


class RpcJsonMapper(JsonMapper):
    """Party resolution through a live RPC proxy (reference:
    RpcObjectMapper) — the remote client's mapper."""

    def __init__(self, ops):
        self._ops = ops

    def well_known_party_from_x500_name(self, name):
        return self._ops.well_known_party_from_x500_name(name)

    def party_from_key(self, key):
        return self._ops.party_from_key(key)
