"""Human-typed strings → ready-to-invoke method calls.

Capability parity with the reference's StringToMethodCallParser
(client/jackson/.../StringToMethodCallParser.kt: "the first word is the
name of the method; the rest is parsed as if it were a Yaml object" whose
keys map to the method's parameters) — the engine behind the shell's
``run``/``flow start`` commands and text-based RPC dispatch.

Syntax::

    someCall note: this is a helpful feature, option: true
    start_flow_dynamic flow: corda_tpu.finance.flows.CashPaymentFlow,
        quantity: 100, currency: GBP, recipient: "O=Bank B, L=Rome, C=GB"

Barewords collapse into strings (quotes only needed around commas/colons);
values convert to each parameter's ANNOTATED type through a ``JsonMapper``
— so parties resolve by X.500 name, hashes parse from hex, amounts from
``"100 GBP"``, exactly as in JSON bodies.
"""

from __future__ import annotations

import dataclasses
import inspect
import typing

from .json_support import JsonMapper, JsonSerializationError


class CallParseError(Exception):
    pass


def _split_top_level(s: str, sep: str) -> list[str]:
    """Split on ``sep`` outside quotes and brackets."""
    out, depth, quote, cur = [], 0, None, []
    for ch in s:
        if quote:
            if ch == quote:
                quote = None
            cur.append(ch)
            continue
        if ch in "\"'":
            quote = ch
            cur.append(ch)
        elif ch in "[{(":
            depth += 1
            cur.append(ch)
        elif ch in ")}]":
            depth -= 1
            cur.append(ch)
        elif ch == sep and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def _parse_scalar(token: str):
    token = token.strip()
    if len(token) >= 2 and token[0] in "\"'" and token[-1] == token[0]:
        return token[1:-1]
    if token == "true":
        return True
    if token == "false":
        return False
    if token == "null":
        return None
    if token.startswith("[") and token.endswith("]"):
        inner = token[1:-1].strip()
        if not inner:
            return []
        return [_parse_scalar(t) for t in _split_top_level(inner, ",")]
    try:
        return int(token)
    except ValueError:
        pass
    try:
        return float(token)
    except ValueError:
        pass
    return token  # bareword → string


def parse_argument_string(s: str) -> dict:
    """``"a: 1, b: hello world, c: [1, 2]"`` → raw key/value dict."""
    s = s.strip()
    if not s:
        return {}
    if s.startswith("{") and s.endswith("}"):
        s = s[1:-1]
    out = {}
    for part in _split_top_level(s, ","):
        part = part.strip()
        if not part:
            continue
        key, colon, raw = part.partition(":")
        if not colon:
            raise CallParseError(f"expected 'key: value', got {part!r}")
        out[key.strip()] = _parse_scalar(raw)
    return out


@dataclasses.dataclass
class ParsedMethodCall:
    """A ready-to-invoke call (reference: ParsedMethodCall — a Callable
    over the target)."""

    target: object
    method_name: str
    kwargs: dict

    def invoke(self):
        return getattr(self.target, self.method_name)(**self.kwargs)

    __call__ = invoke


class StringToMethodCallParser:
    """Parses call strings against ``target``'s public methods, converting
    each argument to the parameter's annotated type via ``mapper``."""

    def __init__(self, target, mapper: JsonMapper | None = None):
        self.target = target
        self.mapper = mapper or JsonMapper()

    def available_commands(self) -> dict:
        """method name → signature string help (reference:
        methodsFromType / the shell's command listing)."""
        out = {}
        for name, fn in inspect.getmembers(self.target, callable):
            if name.startswith("_"):
                continue
            try:
                out[name] = str(inspect.signature(fn))
            except (TypeError, ValueError):
                out[name] = "(...)"
        return out

    def parse(self, line: str) -> ParsedMethodCall:
        line = line.strip()
        if not line:
            raise CallParseError("empty command")
        name, _, rest = line.partition(" ")
        fn = getattr(self.target, name, None)
        if fn is None or not callable(fn) or name.startswith("_"):
            raise CallParseError(f"no such method: {name!r}")
        raw = parse_argument_string(rest)
        try:
            sig = inspect.signature(fn)
        except (TypeError, ValueError):
            return ParsedMethodCall(self.target, name, raw)
        try:
            hints = typing.get_type_hints(fn)
        except Exception:
            hints = {}
        kwargs = {}
        for pname, param in sig.parameters.items():
            if pname in ("self", "args", "kwargs"):
                continue
            if pname not in raw:
                if param.default is inspect.Parameter.empty:
                    raise CallParseError(
                        f"{name}: missing argument {pname!r} "
                        f"(signature {sig})"
                    )
                continue
            value = raw.pop(pname)
            want = hints.get(pname)
            if want is not None:
                try:
                    value = self.mapper.parse(value, want)
                except JsonSerializationError as e:
                    raise CallParseError(
                        f"{name}: argument {pname!r}: {e}"
                    ) from e
            kwargs[pname] = value
        if raw:
            raise CallParseError(
                f"{name}: unknown argument(s) {sorted(raw)} "
                f"(signature {sig})"
            )
        return ParsedMethodCall(self.target, name, kwargs)

    def invoke(self, line: str):
        return self.parse(line).invoke()
