"""RPC client: a typed proxy of the node's CordaRPCOps.

Capability parity with ``CordaRPCClient`` / ``RPCClientProxyHandler``
(client/rpc/.../CordaRPCClient.kt, internal/RPCClientProxyHandler.kt):
``start(username, password)`` yields a connection whose ``proxy`` exposes
every remote operation as a method; feed methods return an ``Observable``
carrying the snapshot plus pushed updates; ``close()`` unsubscribes and
detaches.
"""

from __future__ import annotations

import secrets
import threading
from collections import deque

from corda_tpu.serialization import deserialize, serialize

from .server import (
    Observation,
    RPC_REPLY_TOPIC,
    RPC_REQUEST_TOPIC,
    RpcReply,
    RpcRequest,
)


class RPCException(Exception):
    pass


class Observable:
    """A feed: snapshot + pushed updates (the reference returns rx
    Observables from vaultTrackBy etc.; this is the host-side equivalent
    with callback and blocking-poll consumption)."""

    def __init__(self, subscription_id: str, snapshot, unsubscribe):
        self.subscription_id = subscription_id
        self.snapshot = snapshot
        self._unsubscribe = unsubscribe
        self._lock = threading.Condition()
        self._updates: deque = deque()
        self._callbacks: list = []
        self._closed = False

    def subscribe(self, callback) -> None:
        with self._lock:
            self._callbacks.append(callback)
            backlog = list(self._updates)
        for u in backlog:
            callback(u)

    def poll(self, timeout: float | None = None):
        """Block for the next update (None on timeout/closed)."""
        with self._lock:
            deadline = None
            while not self._updates:
                if self._closed:
                    return None
                if not self._lock.wait(timeout=timeout):
                    return None
            return self._updates.popleft()

    def _push(self, update) -> None:
        with self._lock:
            self._updates.append(update)
            callbacks = list(self._callbacks)
            self._lock.notify_all()
        for cb in callbacks:
            cb(update)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._lock.notify_all()
        self._unsubscribe(self.subscription_id)


_FEED_METHODS = {
    "vault_track", "network_map_feed", "validated_transactions_track",
}


class RPCConnection:
    """One authenticated link to a node; ``proxy`` is self (methods are
    dispatched dynamically)."""

    def __init__(self, messaging, server_name: str, username: str,
                 password: str, timeout_s: float = 30.0):
        self._messaging = messaging
        self._server = server_name
        self._username = username
        self._password = password
        self._timeout_s = timeout_s
        self._lock = threading.Condition()
        self._replies: dict[str, RpcReply] = {}
        self._observables: dict[str, Observable] = {}
        # observations can arrive BEFORE the subscribe reply registers the
        # Observable (the server starts pushing immediately); park them
        self._pending_observations: dict[str, list] = {}
        self._closed = False
        messaging.add_handler(RPC_REPLY_TOPIC, self._on_reply)

    @property
    def proxy(self) -> "RPCConnection":
        return self

    # ------------------------------------------------------------ plumbing
    def _on_reply(self, msg, ack=None) -> None:
        obj = deserialize(msg.payload)
        if isinstance(obj, RpcReply):
            with self._lock:
                self._replies[obj.request_id] = obj
                self._lock.notify_all()
        elif isinstance(obj, Observation):
            update = deserialize(obj.payload_blob)
            with self._lock:
                obs = self._observables.get(obj.subscription_id)
                if obs is None:
                    self._pending_observations.setdefault(
                        obj.subscription_id, []
                    ).append(update)
                    # bound the parking lot: drop oldest orphaned subs
                    # (e.g. a subscribe whose reply errored out)
                    while len(self._pending_observations) > 64:
                        self._pending_observations.pop(
                            next(iter(self._pending_observations))
                        )
            if obs is not None:
                obs._push(update)
        if ack:
            ack()

    def _call(self, method: str, *args, **kwargs):
        if self._closed:
            raise RPCException("connection closed")
        request_id = secrets.token_hex(8)
        req = RpcRequest(
            request_id=request_id,
            username=self._username,
            password=self._password,
            method=method,
            args=tuple(args),
            kwargs_blob=serialize(kwargs) if kwargs else b"",
            reply_to=self._messaging.me.name,
        )
        self._messaging.send(
            self._server, RPC_REQUEST_TOPIC, serialize(req),
            msg_id=f"rpc-{request_id}",
        )
        with self._lock:
            while request_id not in self._replies:
                if not self._lock.wait(timeout=self._timeout_s):
                    raise RPCException(f"RPC {method} timed out")
            reply = self._replies.pop(request_id)
        if not reply.ok:
            raise RPCException(reply.error)
        return deserialize(reply.payload_blob)

    # ------------------------------------------------------------ surface
    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        if name in _FEED_METHODS:
            def feed_call(*args, **kwargs):
                result = self._call(name, *args, **kwargs)
                obs = Observable(
                    result["subscription_id"], result["snapshot"],
                    lambda sid: self._call("unsubscribe", sid),
                )
                with self._lock:
                    sid = result["subscription_id"]
                    self._observables[sid] = obs
                    backlog = self._pending_observations.pop(sid, [])
                for update in backlog:
                    obs._push(update)
                return obs

            return feed_call

        def remote_call(*args, **kwargs):
            return self._call(name, *args, **kwargs)

        return remote_call

    def close(self) -> None:
        with self._lock:
            observables = list(self._observables.values())
            self._observables.clear()
        for obs in observables:
            try:
                obs.close()
            except Exception:
                pass
        self._closed = True


class CordaRPCClient:
    """Entry point (reference: CordaRPCClient(hostAndPort).start(user, pw)).
    ``messaging`` is the client's own endpoint on the shared transport
    (an InMemoryMessagingNetwork node, a broker client, or a gRPC stub in
    deployment); ``server_name`` addresses the node."""

    def __init__(self, messaging, server_name: str):
        self._messaging = messaging
        self._server = server_name

    def start(self, username: str, password: str,
              timeout_s: float = 30.0) -> RPCConnection:
        return RPCConnection(
            self._messaging, self._server, username, password, timeout_s
        )
