"""RPC tier (SURVEY.md §2.1 RPC surface, §2.3 RPC server, §2.5 client/rpc).

The reference serves ``CordaRPCOps`` (core/.../messaging/CordaRPCOps.kt:54,
30+ operations) over Artemis queues with a hand-rolled protocol of
request/reply plus server-pushed Observables (node-api/.../RPCApi.kt:15-50;
server: node/.../messaging/RPCServer.kt; client:
client/rpc/.../CordaRPCClient.kt + RPCClientProxyHandler.kt).

Here the same surface rides the framework's messaging layer (in-memory or
durable broker; gRPC/DCN in deployment): one request topic per node, one
reply topic per client, CBE payloads, and streamed feeds as pushed
``Observation`` messages keyed by subscription id — the Artemis-Observable
muxing redesigned as plain topic streams.
"""

from .ops import CordaRPCOps, PermissionException
from .server import RPCServer
from .client import CordaRPCClient, RPCConnection, Observable
from .json_support import (
    IdentityJsonMapper,
    JsonMapper,
    JsonSerializationError,
    RpcJsonMapper,
)
from .string_calls import (
    CallParseError,
    ParsedMethodCall,
    StringToMethodCallParser,
)

__all__ = [
    "CordaRPCOps", "PermissionException",
    "RPCServer",
    "CordaRPCClient", "RPCConnection", "Observable",
    "IdentityJsonMapper", "JsonMapper", "JsonSerializationError",
    "RpcJsonMapper",
    "CallParseError", "ParsedMethodCall", "StringToMethodCallParser",
]
