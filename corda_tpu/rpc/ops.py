"""The node's remote operations surface.

Capability parity with ``CordaRPCOps`` (core/.../messaging/CordaRPCOps.kt:54):
flow start (:204 startFlowDynamic), vault query/track (:94/:135), network
map snapshot/feed (:197), state machine feed (:69), transaction feed,
notary identities, node info, attachments, registered flows, time.

This class is transport-free — the RPCServer exposes it remotely; in-process
callers (shell, webserver, tests) can use it directly, like the reference's
``CordaRPCOpsImpl`` (node/.../internal/CordaRPCOpsImpl.kt).
"""

from __future__ import annotations

import time as _time

from corda_tpu.crypto.keys import PublicKey
from corda_tpu.flows import FlowLogic
from corda_tpu.flows.api import load_class
from corda_tpu.ledger import CordaX500Name
from corda_tpu.node.vault import PageSpecification, QueryCriteria, Sort


class PermissionException(Exception):
    """RPC user lacks the permission for an operation (reference:
    net.corda.node.services.messaging.RPCOps permission checks)."""


def start_flow_permission(flow_cls_or_path) -> str:
    """Permission string guarding a flow start (reference:
    startFlowPermission<T>())."""
    if isinstance(flow_cls_or_path, str):
        return f"StartFlow.{flow_cls_or_path}"
    from corda_tpu.flows.api import class_path

    return f"StartFlow.{class_path(flow_cls_or_path)}"


class CordaRPCOps:
    """All operations a client may invoke on the node."""

    MAX_RETAINED_HANDLES = 4096

    def __init__(self, services, smm, registered_flow_names=None):
        self._services = services
        self._smm = smm
        self._registered_flows = list(registered_flow_names or [])
        # RPC-started flow handles are retained (bounded) so flow_result
        # works even after the flow finished and the SMM pruned it
        self._handles: dict = {}

    # ------------------------------------------------------------- flows
    def start_flow_dynamic(self, flow_class_path: str, *args, **kwargs):
        """Start a flow by class path; returns the flow id (reference:
        CordaRPCOps.startFlowDynamic :204). The result is retrieved via
        ``flow_result``/the state machine feed — RPC calls never block on
        flow completion."""
        cls = load_class(flow_class_path)
        if not (isinstance(cls, type) and issubclass(cls, FlowLogic)):
            raise PermissionException(
                f"{flow_class_path} is not a startable flow"
            )
        handle = self._smm.start_flow(cls(*args, **kwargs))
        self._handles[handle.flow_id] = handle
        while len(self._handles) > self.MAX_RETAINED_HANDLES:
            self._handles.pop(next(iter(self._handles)))
        return handle.flow_id

    def flow_result(self, flow_id: str, timeout: float | None = None):
        """Block for a started flow's result (the client-side handle's
        ``returnValue`` future in the reference)."""
        handle = self._handles.get(flow_id) or self._smm.handle_of(flow_id)
        if handle is None:
            raise KeyError(f"unknown flow {flow_id}")
        return handle.result.result(timeout=timeout)

    def state_machines_snapshot(self) -> list[str]:
        return self._smm.flows_in_progress()

    def state_machines_detail(self) -> dict:
        """flow id → "running" | "queued" | "parked@<wake key>" — the
        wedged-flow diagnostic surface (what is each live flow waiting
        on)."""
        return self._smm.flows_detail()

    def registered_flows(self) -> list[str]:
        return list(self._registered_flows)

    def kill_flow(self, flow_id: str) -> bool:
        return self._smm.kill_flow(flow_id)

    # ------------------------------------------------------------- vault
    def vault_query_by(self, criteria: QueryCriteria | None = None,
                       paging: PageSpecification | None = None,
                       sorting: Sort | None = None):
        crit = criteria or QueryCriteria()
        return self._services.vault_service.query_by(
            crit, paging=paging, sort=sorting or Sort()
        )

    def vault_track(self, callback):
        """Current page + future updates pushed to ``callback`` (reference:
        vaultTrackBy :135). Over RPC the server bridges the callback into
        an Observation stream."""
        return self._services.vault_service.track(callback)

    # ------------------------------------------------------- transactions
    def transaction(self, tx_id):
        return self._services.validated_transactions.get(tx_id)

    def transaction_count(self) -> int:
        return self._services.validated_transactions.count()

    def validated_transactions_track(self, callback):
        return self._services.validated_transactions.track(callback)

    # -------------------------------------------------------- network map
    def network_map_snapshot(self) -> list:
        return self._services.network_map_cache.all_nodes()

    def network_map_feed(self, callback) -> list:
        return self._services.network_map_cache.track(callback)

    def notary_identities(self) -> list:
        return self._services.network_map_cache.notary_identities

    def node_info(self):
        return self._services.my_info

    def well_known_party_from_x500_name(self, name: CordaX500Name):
        info = self._services.network_map_cache.get_node_by_legal_name(name)
        return info.legal_identity if info else None

    def party_from_key(self, key: PublicKey):
        """reference: CordaRPCOps.partyFromKey — resolve an owning key to
        its well-known party via the identity service, falling back to the
        network map."""
        party = self._services.identity_service.party_from_key(key)
        if party is not None:
            return party
        for info in self._services.network_map_cache.all_nodes():
            if info.legal_identity.owning_key == key:
                return info.legal_identity
        return None

    # -------------------------------------------------------- attachments
    def attachment_exists(self, attachment_id) -> bool:
        return self._services.attachments.has_attachment(attachment_id)

    def upload_attachment(self, data: bytes):
        return self._services.attachments.import_attachment(data)

    def open_attachment(self, attachment_id) -> bytes | None:
        att = self._services.attachments.open_attachment(attachment_id)
        return att.data if att else None

    def untrack(self, callback) -> None:
        """Detach a feed callback from every trackable service (server-side
        unsubscribe cleanup)."""
        self._services.vault_service.untrack(callback)
        self._services.validated_transactions.untrack(callback)
        self._services.network_map_cache.untrack(callback)

    # -------------------------------------------------------- monitoring
    def monitoring_snapshot(self) -> dict:
        """Process + node metrics, sectioned (reference: the Codahale
        registry MonitoringService exposes over JMX). ``serving`` is the
        device scheduler's queue/batch/shed surface (docs/SERVING.md),
        ``profiler`` the kernel profiler's registry mirror (empty until
        the first profiled dispatch; retains the last profiled run after
        disable — the snapshot's ``enabled`` flag says whether numbers
        are live), ``process`` the remaining process-global counters,
        ``node`` this node's own registry (notary meters etc.)."""
        from corda_tpu.node.monitoring import monitoring_snapshot

        snap = monitoring_snapshot()
        snap["node"] = self._services.metrics.snapshot()
        return snap

    def serving_metrics(self) -> dict:
        """Just the ``serving`` section — the operator's first read on a
        slow hot path (queue depth, wait time, batch occupancy, sheds)."""
        from corda_tpu.node.monitoring import node_metrics

        return node_metrics().section("serving.")

    def profiler_snapshot(self) -> dict:
        """The kernel profiler's per-kernel / per-shape-bucket accounting
        (docs/OBSERVABILITY.md §Profiling): compile vs execute wall split,
        batch-efficiency ratios, bytes in/out, achieved rows/sec and the
        roofline fraction. ``{"enabled": false, "kernels": {}}`` while the
        profiler is off (the default)."""
        from corda_tpu.observability import profiler

        return profiler().snapshot()

    def metrics_text(self) -> str:
        """Prometheus text exposition of the process-global AND node-local
        registries (docs/OBSERVABILITY.md §exposition) — counters as
        ``_total``, timers/meters as summaries with p50/p95/p99
        ``quantile`` labels from the reservoirs, plus the labeled
        ``device.*``/``slo.*`` families while those monitors are on. The
        scrape endpoint body."""
        from corda_tpu.observability import metrics_text

        return metrics_text(self._services.metrics)

    def devicemon_snapshot(self) -> dict:
        """The per-device telemetry registry (docs/OBSERVABILITY.md
        §Device telemetry): one entry per ``jax.devices()`` ordinal with
        in-flight depth, dispatch/settle counts, real vs padded rows,
        execute-wall EWMA, completion-heartbeat age, best-effort HBM
        occupancy, and the watchdog's health flag + recent events.
        ``{"enabled": false}`` while the monitor is off (the default)."""
        from corda_tpu.observability.devicemon import devices_section

        return devices_section()

    def slo_status(self) -> dict:
        """The SLO monitor's evaluated objectives (docs/OBSERVABILITY.md
        §SLO monitor): windowed p99 + error/shed rate per objective,
        breach flags, breach count and recent breach/recovery events.
        ``{"enabled": false}`` while SLO tracking is off (the default)."""
        from corda_tpu.observability.slo import slo_section

        return slo_section()

    def timeline_snapshot(self) -> dict:
        """The telemetry timeline's ring snapshot (docs/OBSERVABILITY.md
        §Telemetry timeline): shared sample timestamps plus every series
        ring oldest-first — counter deltas per interval, windowed timer
        p50/p99, per-ordinal device gauges, SLO burn rates — and the mark
        deque. ``{"enabled": false}`` while the timeline is off (the
        default); ``tools_timeline.py`` renders this live."""
        from corda_tpu.observability.timeseries import timeline_section

        return timeline_section()

    def flowprof_snapshot(self) -> dict:
        """Per-flow critical-path phase accounting (docs/OBSERVABILITY.md
        §Critical-path accounting): p50/p99 per phase over closed flows,
        the per-flow-class waterfall (each phase's share of the class's
        total wall — phases sum to wall by construction), and the most
        recent per-flow breakdowns. ``{"enabled": false}`` while phase
        accounting is off (the default)."""
        from corda_tpu.observability.flowprof import flowprof_section

        return flowprof_section()

    def sampler_dump(self, top_n: int = 50) -> dict:
        """The wall-clock sampling profiler's folded flamegraph stacks
        per thread role (docs/OBSERVABILITY.md §Critical-path
        accounting), heaviest first, plus the sampler's measured duty
        cycle. ``{"enabled": false}`` while the sampler is off (the
        default)."""
        from corda_tpu.observability.sampler import active_sampler

        s = active_sampler()
        if s is None:
            return {"enabled": False}
        return s.dump(top_n=top_n)

    def contention_snapshot(self, top_n: int = 16) -> dict:
        """The lock-contention observatory's tables (docs/OBSERVABILITY.md
        §Concurrency observatory): per-allocation-site acquire/contended
        counters with wait/hold p50/p95/p99, the top-contended table
        ranked by total wait, and the holder→waiter wait-edge view.
        ``{"enabled": false}`` while contention timing is off (the
        default)."""
        from corda_tpu.observability.contention import contention_section

        return contention_section(top_n=top_n)

    def speedup_ledger(self) -> dict:
        """The causal profiler's last speedup ledger
        (docs/OBSERVABILITY.md §Causal profiler): phases ranked by
        predicted knee-qps payoff from virtual-speedup experiments, the
        per-(phase, speedup%) cells behind the ranking, and the
        planted-bottleneck validation verdict when the run carried one.
        ``{"enabled": false}`` until a causal run records a ledger."""
        from corda_tpu.observability.causal import causal_section

        return causal_section()

    def flight_dump(self, path: str | None = None,
                    reason: str = "rpc") -> str:
        """Write a black-box flight-recorder dump (docs/OBSERVABILITY.md
        §Flight recorder): recent spans, the full monitoring snapshot,
        per-device state + health events, SLO status, and injected fault
        events as one JSONL file. Returns the path written (a default
        under ``CORDA_TPU_FLIGHT_DIR``/tmp when none is given)."""
        from corda_tpu.observability.slo import flight_dump

        return flight_dump(path, reason=reason)

    def cluster_snapshot(self) -> dict:
        """The federated cluster document (docs/OBSERVABILITY.md
        §Cluster observatory): every cluster member's monitoring
        snapshot + SLO status with mesh-wide rollups (cluster p99,
        per-node deltas, unhealthy-node list). Federates over the
        handle registered via ``set_cluster_handle``; an unclustered
        node answers with a single-node document built from ITS OWN
        ``monitoring_snapshot()``."""
        from corda_tpu.observability.federation import federated_snapshot

        return federated_snapshot(local_ops=self)

    # ------------------------------------------------------------ tracing
    def trace_dump(self, limit: int = 200) -> list:
        """The most recent finished spans from the process tracer's ring
        (span dicts, oldest first) — the raw feed behind trace tooling."""
        from corda_tpu.observability import tracer

        return tracer().dump(limit=limit)

    def trace_for(self, flow_id: str) -> list:
        """Every span of the trace that contains ``flow_id`` (the
        flow→scheduler→batch→notary chain of one request), start-ordered;
        empty when the flow was unsampled or has aged out of the ring."""
        from corda_tpu.observability import tracer

        return tracer().trace_for_attr("flow.id", flow_id)

    # -------------------------------------------------------------- misc
    def current_node_time(self) -> float:
        return (
            self._services.clock() if callable(self._services.clock)
            else _time.time()
        )

    def ping(self) -> str:
        return "pong"
