"""Load-test harness.

Capability parity with the reference's loadtest tool
(tools/loadtest/.../LoadTest.kt:37-69): a load test is four functions —

- ``generate(state, parallelism)`` → list of commands to inject,
- ``interpret(state, command)`` → the expected next state,
- ``execute(command)`` → perform it against the cluster,
- ``gather()`` → observed state, checked against the interpreted one —

run for N generations with a bounded injector pool, optionally under
*disruptions* (kill/restart a node mid-run — Disruption.kt's
kill/restart/strain model) to prove the invariants hold through failures.

The reference drives a deployed cluster over SSH; here the cluster handle
is any object exposing the same operations (an in-process
``MockNetworkNodes`` ensemble or RPC connections to real node processes).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable

import logging

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class LoadTest:
    """One test definition (reference: LoadTest<T, S>)."""

    name: str
    generate: Callable[[Any, int], list]       # (state, parallelism) -> cmds
    interpret: Callable[[Any, Any], Any]       # (state, cmd) -> state'
    execute: Callable[[Any], None]             # cmd -> effect on cluster
    gather: Callable[[], Any]                  # () -> observed state
    initial_state: Any = None


@dataclasses.dataclass
class RunParameters:
    """(reference: LoadTest.RunParameters :61)."""

    parallelism: int = 4
    generate_count: int = 10
    execution_frequency_hz: float | None = 20.0   # None = as fast as possible
    gather_frequency: int = 5                     # check every N generations


@dataclasses.dataclass
class Disruption:
    """A failure injected while load runs (reference: Disruption.kt)."""

    name: str
    apply: Callable[[], Callable[[], None] | None]  # returns undo (or None)
    at_generation: int = 1


class LoadTestError(AssertionError):
    pass


class LoadTestRunner:
    def __init__(self, test: LoadTest, params: RunParameters | None = None,
                 disruptions: list[Disruption] | None = None,
                 rng: random.Random | None = None):
        self.test = test
        self.params = params or RunParameters()
        self.disruptions = list(disruptions or [])
        self.rng = rng or random.Random(0)
        self._metrics_lock = threading.Lock()
        self.metrics = {"executed": 0, "failed": 0, "gathers": 0,
                        "disruptions": 0}

    def run(self) -> dict:
        """Run the test. The returned metrics carry a ``walls`` section
        timing each stage SEPARATELY — ``generate_s`` (command generation
        + model interpretation), ``execute_s`` (submit of the first
        command to resolution of the last future, per generation),
        ``gather_s`` (state collection + divergence check) — plus
        ``executed_per_s`` computed against the execute wall alone.
        Closed-loop harnesses classically overstate latency and
        understate capacity by folding generator and checker time into
        the measured window (coordinated omission's sibling); splitting
        the walls keeps the throughput figure honest. Pool/disruption
        setup is excluded from all three. For open-loop (Poisson
        arrival) measurement use ``corda_tpu.tools.loadharness``."""
        state = self.test.initial_state
        undos: list = []
        interval = (
            1.0 / self.params.execution_frequency_hz
            if self.params.execution_frequency_hz else 0.0
        )
        # setup (pool spin-up, disruption bookkeeping) stays outside the
        # timed stages
        pool = ThreadPoolExecutor(max_workers=self.params.parallelism)
        gen_wall = exec_wall = gather_wall = 0.0
        try:
            for generation in range(self.params.generate_count):
                for d in self.disruptions:
                    if d.at_generation == generation:
                        logger.info("injecting disruption %r", d.name)
                        undo = d.apply()
                        if undo:
                            undos.append(undo)
                        with self._metrics_lock:
                            self.metrics["disruptions"] += 1
                t0 = time.monotonic()
                commands = self.test.generate(state, self.params.parallelism)
                # interpret first: expected state is defined by the model,
                # not by what happened to succeed
                for cmd in commands:
                    state = self.test.interpret(state, cmd)
                t1 = time.monotonic()
                gen_wall += t1 - t0
                futures = []
                for cmd in commands:
                    futures.append(pool.submit(self._execute_one, cmd))
                    if interval:
                        time.sleep(interval)
                for f in futures:
                    f.result()
                exec_wall += time.monotonic() - t1
                if (generation + 1) % self.params.gather_frequency == 0:
                    t2 = time.monotonic()
                    self._gather_and_check(state)
                    gather_wall += time.monotonic() - t2
            t2 = time.monotonic()
            self._gather_and_check(state)
            gather_wall += time.monotonic() - t2
        finally:
            for undo in undos:
                try:
                    undo()
                except Exception:
                    logger.exception("disruption undo failed")
            pool.shutdown(wait=True)
        executed = self.metrics["executed"]
        return dict(
            self.metrics,
            final_state=state,
            walls={
                "generate_s": gen_wall,
                "execute_s": exec_wall,
                "gather_s": gather_wall,
                "total_s": gen_wall + exec_wall + gather_wall,
            },
            executed_per_s=(executed / exec_wall) if exec_wall > 0 else 0.0,
        )

    def _execute_one(self, cmd) -> None:
        try:
            self.test.execute(cmd)
            with self._metrics_lock:
                self.metrics["executed"] += 1
        except Exception:
            logger.exception("command execution failed")
            with self._metrics_lock:
                self.metrics["failed"] += 1

    def _gather_and_check(self, expected) -> None:
        observed = self.test.gather()
        with self._metrics_lock:
            self.metrics["gathers"] += 1
        if observed != expected:
            raise LoadTestError(
                f"{self.test.name}: observed state diverged.\n"
                f"  expected: {expected}\n  observed: {observed}"
            )


# ------------------------------------------------- built-in test shapes

def self_issue_test(nodes: dict, notary, amounts=(100, 1000)) -> LoadTest:
    """Every command issues cash on a random node; the model tracks each
    node's expected balance (reference: SelfIssueTest.kt)."""
    from corda_tpu.finance import CashIssueFlow, CashState

    rng = random.Random(7)
    names = sorted(nodes)

    def generate(state, parallelism):
        return [
            (rng.choice(names), rng.randrange(*amounts))
            for _ in range(parallelism)
        ]

    def interpret(state, cmd):
        name, qty = cmd
        state = dict(state)
        state[name] = state.get(name, 0) + qty
        return state

    def execute(cmd):
        name, qty = cmd
        nodes[name].run_flow(CashIssueFlow(qty, "GBP", b"\x11", notary))

    def gather():
        return {
            name: sum(
                sr.state.data.amount.quantity
                for sr in node.services.vault_service.unconsumed_states(
                    CashState
                )
            )
            for name, node in nodes.items()
        }

    return LoadTest(
        name="SelfIssue",
        generate=generate, interpret=interpret, execute=execute,
        gather=gather, initial_state={},
    )


def notary_service_storm_test(
    service, stxs: list, resolve, chunk: int = 64
) -> LoadTest:
    """Drive a ``BatchedNotaryService``'s async request path at full rate —
    the service-level notary storm (reference: NotaryTest.kt:22-50 floods
    the notary with issue+move pairs; here the pre-built move transactions
    submit through ``service.request`` and the model checks every one
    committed exactly once).

    ``generate`` hands out chunks of pre-built transactions, ``execute``
    fire-and-forgets them into the batching window (throughput comes from
    the service's pipeline, not from injector threads blocking on
    futures), and ``gather`` drains all futures and reads the committed-tx
    count off the uniqueness provider.
    """
    futures: list = []

    def generate(state, parallelism):
        cmds = []
        start = state
        for _ in range(parallelism):
            part = stxs[start : start + chunk]
            if not part:
                break
            cmds.append(part)
            start += len(part)
        return cmds

    def interpret(state, cmd):
        return state + len(cmd)

    def execute(cmd):
        for stx in cmd:
            futures.append(service.request(stx, resolve, "loadtest"))

    def gather():
        for f in list(futures):
            f.result(timeout=120)
        return service.uniqueness.committed_txs()

    return LoadTest(
        name="NotaryServiceStorm",
        generate=generate, interpret=interpret, execute=execute,
        gather=gather, initial_state=0,
    )


def notarisation_storm_test(nodes: dict, notary_party) -> LoadTest:
    """Issue+move pairs through FinalityFlow — the notary-storm shape
    (reference: NotaryTest.kt:22-50). The model counts notarised moves;
    gather reads the notary's committed-state count."""
    from corda_tpu.finance import CashIssueFlow, CashPaymentFlow

    rng = random.Random(13)
    names = sorted(nodes)

    def generate(state, parallelism):
        out = []
        for _ in range(parallelism):
            a, b = rng.sample(names, 2)
            out.append((a, b, rng.randrange(10, 100)))
        return out

    def interpret(state, cmd):
        return state + 1

    def execute(cmd):
        src, dst, qty = cmd
        nodes[src].run_flow(
            CashIssueFlow(qty, "GBP", b"\x12", notary_party)
        )
        nodes[src].run_flow(
            CashPaymentFlow(qty, "GBP", nodes[dst].party)
        )

    def gather():
        # moves notarised so far == model count (issues skip the notary)
        notary_node = next(
            n for n in nodes.values()
            if n.services.notary_service is not None
        )
        return notary_node.services.notary_service.uniqueness.committed_txs()

    return LoadTest(
        name="NotarisationStorm",
        generate=generate, interpret=interpret, execute=execute,
        gather=gather, initial_state=0,
    )
