"""DemoBench: spawn and drive a local node ensemble interactively.

Capability parity with the reference's DemoBench desktop app
(tools/demobench/.../DemoBench.kt — spawn local nodes with attached
terminals, add nodes on demand, tear everything down on exit). The TPU
build's equivalent is terminal-native: an ensemble manager over the
process driver (`testing/driver.py`) with an interactive console —
``add`` spawns another node, ``shell <node>`` attaches the interactive
shell over RPC, ``explorer <node>`` serves the browser explorer.

    python -m corda_tpu.tools.demobench            # notary + 2 banks
    python -m corda_tpu.tools.demobench --secure   # authenticated fabric
"""

from __future__ import annotations

import sys


class DemoBench:
    """Programmatic ensemble manager (the DemoBench window, sans window)."""

    def __init__(self, base_dir: str | None = None, secure: bool = False):
        from corda_tpu.testing.driver import DriverDSL

        import tempfile

        self._dsl = DriverDSL(
            base_dir or tempfile.mkdtemp(prefix="corda-tpu-demobench-"),
            secure=secure,
        )
        self._explorers: list = []

    # ------------------------------------------------------------- nodes
    @property
    def nodes(self):
        return list(self._dsl.nodes)

    def add_notary(self, name: str = "O=Notary,L=Zurich,C=CH"):
        return self._dsl.start_node(name, notary=True)

    def add_node(self, name: str):
        return self._dsl.start_node(name)

    def rpc(self, node):
        return self._dsl.rpc(node)

    def shell(self, node, out=sys.stdout):
        """An InteractiveShell attached to the node over RPC (the
        reference's per-node terminal pane)."""
        from corda_tpu.tools.shell import InteractiveShell

        return InteractiveShell(self.rpc(node).proxy, out=out)

    def explorer(self, node):
        """Serve the browser explorer for one node; returns the server."""
        from corda_tpu.tools.explorer import ExplorerServer

        server = ExplorerServer(self.rpc(node).proxy).start()
        self._explorers.append(server)
        return server

    def shutdown(self) -> None:
        for ex in self._explorers:
            try:
                ex.stop()
            except Exception:
                pass
        self._dsl.shutdown()

    def __enter__(self) -> "DemoBench":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="corda-tpu-demobench")
    ap.add_argument("--secure", action="store_true",
                    help="run the ensemble over the authenticated fabric")
    ap.add_argument("--banks", type=int, default=2)
    args = ap.parse_args(argv)

    with DemoBench(secure=args.secure) as bench:
        print("starting notary…")
        bench.add_notary()
        handles = []
        for i in range(args.banks):
            name = f"O=Bank {chr(65 + i)},L=London,C=GB"
            print(f"starting {name}…")
            handles.append(bench.add_node(name))
        print("\nensemble up:")
        for h in bench.nodes:
            print(f"  {h.name}  (pid {h.process.pid})")
        print(
            "\ncommands: nodes | shell <n> | explorer <n> | add <X500> | quit"
        )
        while True:
            try:
                line = input("demobench> ").strip()
            except (EOFError, KeyboardInterrupt):
                break
            if not line:
                continue
            cmd, _, rest = line.partition(" ")
            if cmd in ("quit", "exit"):
                break
            elif cmd == "nodes":
                for i, h in enumerate(bench.nodes):
                    state = "up" if h.alive else "DOWN"
                    print(f"  [{i}] {h.name}  {state}")
            elif cmd == "add" and rest:
                bench.add_node(rest)
                print("started")
            elif cmd == "shell" and rest.isdigit():
                shell = bench.shell(bench.nodes[int(rest)])
                print("attached — 'quit' returns to demobench")
                shell.repl()
            elif cmd == "explorer" and rest.isdigit():
                server = bench.explorer(bench.nodes[int(rest)])
                print(f"explorer at http://127.0.0.1:{server.port}/")
            else:
                print("commands: nodes | shell <n> | explorer <n> "
                      "| add <X500> | quit")
        print("shutting down…")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
