"""Dependency graphing of the framework's packages.

Capability parity with the reference's tools/graphs (gradle scripts that
render the module dependency graph). Here the build units are the
``corda_tpu`` subpackages; their import edges are extracted from source
and rendered as Graphviz DOT — the same at-a-glance architecture view.

    python -m corda_tpu.tools.graphs            # DOT on stdout
    python -m corda_tpu.tools.graphs --out deps.dot
"""

from __future__ import annotations

import ast
import sys
from collections import defaultdict
from pathlib import Path


def package_edges(root: str | Path | None = None) -> dict[str, set[str]]:
    """``{subpackage: {imported subpackages}}`` from MODULE-LEVEL imports.

    Function-level (deferred) imports are excluded on purpose: they are
    the framework's sanctioned mechanism for referencing a higher layer
    from a lower one without an import-time dependency, so only the
    top-level statements express the layering contract."""
    import corda_tpu

    root = Path(root) if root else Path(corda_tpu.__file__).parent
    edges: dict[str, set[str]] = defaultdict(set)
    for py in sorted(root.rglob("*.py")):
        rel = py.relative_to(root)
        src_pkg = rel.parts[0] if len(rel.parts) > 1 else rel.stem
        if src_pkg == "__init__":
            src_pkg = "(root)"
        try:
            tree = ast.parse(py.read_text(), filename=str(py))
        except SyntaxError:
            continue
        for node in tree.body:
            target = None
            if isinstance(node, ast.ImportFrom):
                if node.level > 0:
                    continue  # intra-package relative import
                target = node.module or ""
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith("corda_tpu."):
                        parts = alias.name.split(".")
                        if len(parts) > 1 and parts[1] != src_pkg:
                            edges[src_pkg].add(parts[1])
                continue
            if target and target.startswith("corda_tpu."):
                dst = target.split(".")[1]
                if dst != src_pkg:
                    edges[src_pkg].add(dst)
    return dict(edges)


def to_dot(edges: dict[str, set[str]]) -> str:
    lines = [
        "digraph corda_tpu_packages {",
        "  rankdir=BT;",
        '  node [shape=box, style="rounded,filled", fillcolor="#eef"];',
    ]
    nodes = sorted(set(edges) | {d for ds in edges.values() for d in ds})
    for n in nodes:
        lines.append(f'  "{n}";')
    for src in sorted(edges):
        for dst in sorted(edges[src]):
            lines.append(f'  "{src}" -> "{dst}";')
    lines.append("}")
    return "\n".join(lines)


def layering_violations(edges: dict[str, set[str]]) -> list[tuple[str, str]]:
    """Edges that point UP the layer map (SURVEY §1) — the check the
    graph exists to make cheap. Lower number = lower layer."""
    # our layering, not the reference's: deterministic serialization and
    # the native-build helper are FOUNDATIONAL here (crypto registers its
    # wire types at import; ops loads C++ engines), unlike the JVM stack
    # where serialization sits above the data model
    layer = {
        # observability is foundational on purpose: every layer opens
        # spans / records metrics, so the tracer must sit below them all
        # (its only corda_tpu imports are function-level)
        "native_build": 0, "serialization": 0, "observability": 0,
        "ops": 1, "crypto": 1,  # mutually layered: ops hashes crypto's
                                # types, crypto dispatches to ops kernels
        "ledger": 2,
        "parallel": 3, "messaging": 3,
        "flows": 4, "verifier": 4,
        "node": 5, "notary": 5,
        "rpc": 6,
        "finance": 7, "confidential": 7,
        "samples": 8, "tools": 8, "testing": 8,
    }
    bad = []
    for src, dsts in edges.items():
        for dst in dsts:
            if layer.get(src, 99) < layer.get(dst, 99):
                bad.append((src, dst))
    return sorted(bad)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="corda-tpu-graphs")
    ap.add_argument("--out", default=None, help="write DOT here (else stdout)")
    ap.add_argument("--check", action="store_true",
                    help="fail on layering violations")
    args = ap.parse_args(argv)
    edges = package_edges()
    dot = to_dot(edges)
    if args.out:
        Path(args.out).write_text(dot + "\n")
        print(f"wrote {args.out} ({len(edges)} packages)")
    else:
        print(dot)
    if args.check:
        bad = layering_violations(edges)
        if bad:
            for src, dst in bad:
                print(f"LAYERING: {src} -> {dst}", file=sys.stderr)
            return 1
        print("layering ok", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
