"""Node Explorer: a browser-based ledger/network observability UI.

Capability parity with the reference's JavaFX Explorer
(tools/explorer/.../Main.kt + ExplorerSimulation.kt — a GUI over the RPC
feeds showing the vault, transactions, network map and state machines).
The TPU build has no desktop toolkit; the same observability ships as a
self-contained single-page app (vanilla JS, auto-refreshing) served by a
small HTTP façade over ``CordaRPCOps`` — the identical data the JavaFX
client binds via client/jfx, rendered in any browser.

    python -m corda_tpu.tools.explorer --config node.conf   # standalone
    ExplorerServer(ops).start()                             # embedded
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse

from .webserver import _jsonable

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>corda_tpu explorer</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f6f8;color:#1c2733}
 header{background:#1c2733;color:#fff;padding:10px 20px;display:flex;
        justify-content:space-between;align-items:baseline}
 header h1{font-size:18px;margin:0} header span{font-size:12px;opacity:.8}
 main{display:grid;grid-template-columns:1fr 1fr;gap:14px;padding:14px}
 section{background:#fff;border-radius:6px;box-shadow:0 1px 3px rgba(0,0,0,.12);
         padding:12px;overflow:auto;max-height:44vh}
 h2{font-size:14px;margin:0 0 8px;color:#44546a}
 table{border-collapse:collapse;width:100%;font-size:12px}
 td,th{border-bottom:1px solid #e3e8ee;padding:4px 6px;text-align:left;
       font-family:ui-monospace,monospace;word-break:break-all}
 th{color:#7a8aa0;font-weight:600}
 .pill{display:inline-block;background:#e8f0fe;border-radius:8px;
       padding:1px 8px;font-size:11px}
</style></head><body>
<header><h1>corda_tpu explorer</h1><span id="who"></span></header>
<main>
 <section><h2>Network map</h2><table id="peers"></table></section>
 <section><h2>Notaries</h2><table id="notaries"></table></section>
 <section><h2>Vault (unconsumed states)</h2><table id="vault"></table></section>
 <section><h2>State machines (in flight)</h2><table id="flows"></table></section>
 <section style="grid-column:1/3"><h2>Registered flows</h2>
   <div id="regflows"></div></section>
</main>
<script>
async function j(u){const r=await fetch(u);return r.json()}
function rows(el, header, data, f){
  const t=document.getElementById(el);
  t.innerHTML='<tr>'+header.map(h=>`<th>${h}</th>`).join('')+'</tr>'+
    data.map(d=>'<tr>'+f(d).map(c=>`<td>${c}</td>`).join('')+'</tr>').join('');
}
async function refresh(){
  try{
    const s=await j('/api/status');
    document.getElementById('who').textContent=
      `${s.identity} — ${new Date(s.time*1000).toISOString()}`;
    const peers=await j('/api/peers');
    rows('peers',['legal name','addresses'],peers,
         p=>[p.legal_name,p.addresses.join(', ')]);
    const nots=await j('/api/notaries');
    rows('notaries',['notary'],nots,n=>[n]);
    const v=await j('/api/vault');
    rows('vault',['ref','contract state'],v.states,
         s=>[s.ref,JSON.stringify(s.state).slice(0,300)]);
    const f=await j('/api/flows');
    rows('flows',['flow id'],f.map(x=>[x]),x=>x);
    document.getElementById('regflows').innerHTML=
      (await j('/api/registered-flows'))
        .map(n=>`<span class="pill">${n}</span> `).join('');
  }catch(e){console.error(e)}
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class ExplorerServer:
    """Serves the explorer page + its JSON feeds over a CordaRPCOps-shaped
    object (local or an RPC connection proxy)."""

    def __init__(self, ops, host: str = "127.0.0.1", port: int = 0):
        self._ops = ops
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply_json(self, payload) -> None:
                body = json.dumps(_jsonable(payload)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_html(self, page: str) -> None:
                body = page.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    outer._get(self)
                except Exception as e:
                    try:
                        self._reply_json(
                            {"error": f"{type(e).__name__}: {e}"}
                        )
                    except Exception:
                        pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_port
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ routes
    def _get(self, req) -> None:
        path = urlparse(req.path).path.rstrip("/") or "/"
        ops = self._ops
        if path == "/":
            req._reply_html(_PAGE)
        elif path == "/api/status":
            info = ops.node_info()
            req._reply_json({
                "identity": str(info.legal_identity.name),
                "time": ops.current_node_time(),
            })
        elif path == "/api/peers":
            req._reply_json([
                {
                    "legal_name": str(i.legal_identity.name),
                    "addresses": list(i.addresses),
                }
                for i in ops.network_map_snapshot()
            ])
        elif path == "/api/notaries":
            req._reply_json([str(p.name) for p in ops.notary_identities()])
        elif path == "/api/vault":
            page = ops.vault_query_by()
            req._reply_json({
                "total": page.total_states_available,
                "states": [
                    {"ref": str(sr.ref), "state": sr.state.data}
                    for sr in page.states
                ],
            })
        elif path == "/api/flows":
            req._reply_json(ops.state_machines_snapshot())
        elif path == "/api/registered-flows":
            req._reply_json(ops.registered_flows())
        else:
            req._reply_json({"error": f"unknown path {path!r}"})

    # --------------------------------------------------------- lifecycle
    def start(self) -> "ExplorerServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="explorer"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def main(argv=None) -> int:
    import argparse

    from corda_tpu.messaging import BrokerMessagingClient, DurableQueueBroker
    from corda_tpu.rpc import CordaRPCClient

    ap = argparse.ArgumentParser(prog="corda-tpu-explorer")
    ap.add_argument("--broker", default="broker.db",
                    help="shared broker file of the node ensemble")
    ap.add_argument("--node", required=True,
                    help="X.500 name of the node to explore")
    ap.add_argument("--username", default="explorer")
    ap.add_argument("--password", default="explorer")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)

    broker = DurableQueueBroker(args.broker)
    endpoint = BrokerMessagingClient(broker, "explorer-ui")
    conn = CordaRPCClient(endpoint, args.node).start(
        args.username, args.password
    )
    server = ExplorerServer(conn.proxy, port=args.port).start()
    print(f"explorer serving http://127.0.0.1:{server.port}/")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
