"""Open-loop SLO-attainment harness.

The closed-loop harness (``loadtest.py``) measures what N injector
threads can push: each thread waits for its command to finish before
issuing the next, so the moment the system slows down the offered load
*politely backs off* — latency quantiles flatten exactly when they
should explode, and the measured "capacity" is really "capacity at the
concurrency the harness happened to pick" (coordinated omission).

This harness is OPEN-LOOP: arrivals are a seeded Poisson process at a
fixed target rate, and the arrival clock NEVER waits for completions.
A flow's latency is measured from its *scheduled arrival time* — if the
system (or the submitting thread) falls behind, the backlog shows up in
p99 instead of silently stretching the inter-arrival gaps. Offered load
the system cannot absorb accumulates as in-flight backlog until the
``max_inflight`` bound, past which arrivals are SHED and counted (the
open-loop analogue of an admission reject — the arrival still happened).

A run is a stepped qps ramp. Each step is scored through a private
``SLOMonitor`` (PR 7's attainment machinery, breach-latch only): the
windowed p99 and the error+shed rate are checked against the configured
objective, and the KNEE is the highest step whose SLO held. Per-step
flowprof waterfalls (``configure_flowprof(reset=True)`` between steps)
say where the wall went as the knee approaches — queue wait and lock
wait grow, device execute does not. Results land in ``LOADTEST.json``
(schema checked by ``tools_perf_gate.py --check-schema``); the CLI is
``tools_loadgen.py``. Knobs and method: docs/LOAD_HARNESS.md. Metric
names (``loadharness.*``): docs/OBSERVABILITY.md §"Critical-path
accounting".

Toggles compose with the chaos/durability/resilience tiers: a
``FaultPlan`` runs the ramp under injected message loss, ``durable=True``
puts every node on WAL-backed checkpoints (fsync wait appears in the
waterfall), ``resilience=True`` serves verification through a
self-healing scheduler.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import tempfile
import threading
import time

LOADTEST_SCHEMA = 1


@dataclasses.dataclass
class HarnessConfig:
    """One ramp's knobs (docs/LOAD_HARNESS.md has the full table)."""

    qps_steps: tuple = (4.0, 8.0, 16.0)
    step_duration_s: float = 5.0
    drain_timeout_s: float = 30.0
    seed: int = 2026
    # the SLO each step is scored against
    p99_slo_s: float = 2.0
    max_error_rate: float = 0.05
    min_samples: int = 5
    # open-loop shed bound: arrivals past this in-flight depth are shed
    max_inflight: int = 256
    # workload: "payment" (issue setup + CashPaymentFlow arrivals, full
    # flow→verify→notary path) or "issue" (CashIssueFlow arrivals only,
    # no notary leg — cheaper, for pure engine saturation)
    workload: str = "payment"
    use_device: bool = False        # device-batched signature verify
    # toggles
    chaos: object | None = None     # a faultinject.FaultPlan, or None
    durable: bool = False           # WAL-backed checkpoints on every node
    resilience: bool = False        # self-healing serving policy
    flowprof: bool = True           # per-step waterfalls
    sampler: bool = False           # attach folded stacks to the result
    netstats: bool = True           # per-step edge retransmit/transit


def _quantile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


class _StepStats:
    """One step's outcome ledger (thread-safe: completions land from
    flow-worker callback threads while the arrival clock runs)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latencies: list[float] = []
        self.errors = 0
        self.shed = 0
        self.offered = 0

    def complete(self, latency_s: float, error: bool) -> None:
        with self.lock:
            if error:
                self.errors += 1
            else:
                self.latencies.append(latency_s)


class LoadHarness:
    """Builds the mocknet fixture, runs the ramp, scores the steps."""

    def __init__(self, config: HarnessConfig | None = None):
        self.config = config or HarnessConfig()
        self._rng = random.Random(self.config.seed)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._idle = threading.Condition(self._inflight_lock)

    # ------------------------------------------------------------ fixture
    def _build(self, stack):
        """Create the 3-node mocknet (+ optional durability/resilience/
        chaos tiers) on ``stack`` (an ExitStack owning teardown)."""
        from corda_tpu.testing.mocknet import MockNetworkNodes
        from corda_tpu.verifier import BatchedVerifierService

        cfg = self.config
        if cfg.chaos is not None:
            from corda_tpu.faultinject import FaultInjector
            from corda_tpu.faultinject import clear as clear_injector
            from corda_tpu.faultinject import install as install_injector

            install_injector(FaultInjector(cfg.chaos))
            stack.callback(clear_injector)
        if cfg.resilience:
            from corda_tpu.serving import ResiliencePolicy, configure_scheduler

            configure_scheduler(
                use_device_default=cfg.use_device,
                resilience=ResiliencePolicy(flight_dump_on_quarantine=False),
            )
        net = stack.enter_context(MockNetworkNodes())
        checkpoints = None
        if cfg.durable:
            from corda_tpu.durability import DurableStore
            from corda_tpu.flows.checkpoints import WalCheckpointStorage

            base = stack.enter_context(
                tempfile.TemporaryDirectory(prefix="loadharness-")
            )

            def checkpoints(name):
                return WalCheckpointStorage(
                    DurableStore(os.path.join(base, name), name="flows")
                )
        sender = net.create_node(
            "HarnessA",
            checkpoints=None if checkpoints is None else checkpoints("a"),
        )
        receiver = net.create_node(
            "HarnessB",
            checkpoints=None if checkpoints is None else checkpoints("b"),
        )
        notary = net.create_notary_node("HarnessNotary")
        vsvc = BatchedVerifierService(use_device=cfg.use_device)
        sender.services.transaction_verifier_service = vsvc
        stack.callback(vsvc.shutdown)
        return net, sender, receiver, notary

    # ------------------------------------------------------------- arrival
    def _start_request(self, sender, receiver, notary, stats: _StepStats,
                       scheduled_t: float) -> None:
        """Submit one arrival (non-blocking) and wire its completion back
        into ``stats``. Latency runs from the SCHEDULED arrival time."""
        from corda_tpu.finance import CashIssueFlow, CashPaymentFlow

        cfg = self.config
        if cfg.workload == "payment":
            flow = CashPaymentFlow(1, "GBP", receiver.party)
        else:
            flow = CashIssueFlow(1, "GBP", b"\x77", notary.party)
        with self._inflight_lock:
            if self._inflight >= cfg.max_inflight:
                stats.shed += 1
                return
            self._inflight += 1
        try:
            handle = sender.smm.start_flow(flow)
        except Exception:
            with self._inflight_lock:
                self._inflight -= 1
                self._idle.notify_all()
            stats.complete(0.0, error=True)
            return

        def done(fut, _t0=scheduled_t):
            latency = time.monotonic() - _t0
            err = fut.exception() is not None
            stats.complete(latency, error=err)
            with self._inflight_lock:
                self._inflight -= 1
                self._idle.notify_all()

        handle.result.add_done_callback(done)

    def _drain(self, deadline_s: float) -> bool:
        with self._inflight_lock:
            return self._idle.wait_for(
                lambda: self._inflight == 0, timeout=deadline_s
            )

    # ---------------------------------------------------------------- run
    def _run_step(self, qps: float, fixture) -> dict:
        """One open-loop step: Poisson arrivals at ``qps`` for
        ``step_duration_s``, drain, score through a private SLOMonitor."""
        from corda_tpu.node.monitoring import node_metrics
        from corda_tpu.observability.slo import SLOMonitor, SLOObjective

        net, sender, receiver, notary = fixture
        cfg = self.config
        stats = _StepStats()
        monitor = SLOMonitor(
            objectives=(SLOObjective(
                name=f"loadharness@{qps:g}qps", priority="harness",
                p99_s=cfg.p99_slo_s, max_error_rate=cfg.max_error_rate,
                window_s=cfg.step_duration_s + cfg.drain_timeout_s + 60.0,
                min_samples=cfg.min_samples,
            ),),
            breach_handler=None,  # latch only: scoring, not paging
        )
        if cfg.flowprof:
            from corda_tpu.observability.flowprof import configure_flowprof

            configure_flowprof(enabled=True, reset=True)
        if cfg.netstats:
            from corda_tpu.messaging.netstats import configure_netstats

            configure_netstats(enabled=True, reset=True)
        t_start = time.monotonic()
        next_arrival = t_start
        end = t_start + cfg.step_duration_s
        offered = 0
        while next_arrival < end:
            now = time.monotonic()
            if next_arrival > now:
                time.sleep(next_arrival - now)
            # the arrival HAPPENS at its scheduled instant even when the
            # clock thread woke late — open-loop latency runs from here
            self._start_request(sender, receiver, notary, stats,
                                next_arrival)
            offered += 1
            next_arrival += self._rng.expovariate(qps)
        stats.offered = offered
        drained = self._drain(cfg.drain_timeout_s)
        step_wall = time.monotonic() - t_start
        if not drained:
            # whatever is still in flight timed out the drain: score each
            # as an error with the drain-bounded latency (open-loop: they
            # were offered, so they count)
            with self._inflight_lock:
                stuck = self._inflight
            for _ in range(stuck):
                stats.complete(step_wall, error=True)
        # feed + evaluate the private SLO monitor
        with stats.lock:
            lats = sorted(stats.latencies)
            errors = stats.errors
            shed = stats.shed
        for lat in lats:
            monitor.observe("harness", lat)
        for _ in range(errors):
            monitor.observe("harness", None, error=True)
        for _ in range(shed):
            monitor.observe("harness", None, error=True)
        statuses = monitor.evaluate()
        slo_ok = bool(statuses) and not any(s["breached"] for s in statuses)
        completed = len(lats)
        denom = completed + errors + shed
        step = {
            "qps": qps,
            "offered": offered,
            "completed": completed,
            "errors": errors,
            "shed": shed,
            "shed_rate": (shed / denom) if denom else 0.0,
            "error_rate": ((errors + shed) / denom) if denom else 0.0,
            "p50_s": _quantile(lats, 0.5),
            "p99_s": _quantile(lats, 0.99),
            "drained": drained,
            "wall_s": step_wall,
            "slo_ok": slo_ok,
            "slo": statuses,
        }
        # network-path telemetry (always numeric — the schema gate
        # requires the keys even when the netstats toggle is off)
        retransmits, net_p99 = 0, 0.0
        if cfg.netstats:
            from corda_tpu.messaging.netstats import active_netstats

            nets = active_netstats()
            if nets is not None:
                retransmits = nets.total_retransmits()
                net_p99 = nets.transit_p99_s()
        step["retransmits"] = retransmits
        step["net_transit_p99_s"] = net_p99
        if cfg.flowprof:
            step["waterfall"] = self._waterfall()
        m = node_metrics()
        m.timer("loadharness.step_p99_s").update(step["p99_s"])
        m.counter("loadharness.offered").inc(offered)
        m.counter("loadharness.shed").inc(shed)
        return step

    def _waterfall(self) -> dict:
        """The step's flowprof aggregate for the workload's flow class:
        phase seconds + each phase's share of the class's total wall
        (phases sum to wall by construction — the schema gate checks)."""
        from corda_tpu.observability.flowprof import flowprof_section

        section = flowprof_section()
        classes = section.get("classes", {})
        want = ("CashPaymentFlow" if self.config.workload == "payment"
                else "CashIssueFlow")
        for cls, agg in classes.items():
            if cls.endswith(want):
                return {
                    "flow_class": cls,
                    "flows": agg["flows"],
                    "wall_s": agg["wall_s"],
                    "phases": agg["phases"],
                    "shares": agg["shares"],
                }
        return {"flow_class": want, "flows": 0, "wall_s": 0.0,
                "phases": {}, "shares": {}}

    def run(self) -> dict:
        """The full ramp. Returns the LOADTEST payload (see
        ``write_loadtest`` for the file half)."""
        import contextlib

        from corda_tpu.finance import CashIssueFlow

        cfg = self.config
        sampler_obj = None
        if cfg.sampler:
            from corda_tpu.observability.sampler import configure_sampler

            sampler_obj = configure_sampler(enabled=True, reset=True)
        try:
            with contextlib.ExitStack() as stack:
                fixture = self._build(stack)
                net, sender, receiver, notary = fixture
                # ---- setup (UNMEASURED): pre-issue one 1-GBP state per
                # expected payment so arrivals never contend on selection
                # and never run out of cash mid-step
                if cfg.workload == "payment":
                    expected = sum(
                        int(q * cfg.step_duration_s * 1.5) + 8
                        for q in cfg.qps_steps
                    )
                    for _ in range(expected):
                        sender.run_flow(
                            CashIssueFlow(1, "GBP", b"\x77", notary.party)
                        )
                steps = [self._run_step(q, fixture) for q in cfg.qps_steps]
        finally:
            if cfg.flowprof:
                from corda_tpu.observability.flowprof import (
                    configure_flowprof,
                )

                configure_flowprof(enabled=False, reset=True)
            if cfg.netstats:
                from corda_tpu.messaging.netstats import configure_netstats

                configure_netstats(enabled=False, reset=True)
            if sampler_obj is not None:
                from corda_tpu.observability.sampler import configure_sampler

                configure_sampler(enabled=False)
            if cfg.resilience:
                from corda_tpu.serving.scheduler import shutdown_scheduler

                shutdown_scheduler()
        knee = None
        for step in steps:
            if step["slo_ok"]:
                knee = step
        result = {
            "schema": LOADTEST_SCHEMA,
            "mode": "open-loop-poisson",
            "config": {
                "qps_steps": list(cfg.qps_steps),
                "step_duration_s": cfg.step_duration_s,
                "seed": cfg.seed,
                "p99_slo_s": cfg.p99_slo_s,
                "max_error_rate": cfg.max_error_rate,
                "max_inflight": cfg.max_inflight,
                "workload": cfg.workload,
                "use_device": cfg.use_device,
                "chaos": cfg.chaos is not None,
                "durable": cfg.durable,
                "resilience": cfg.resilience,
            },
            "steps": steps,
            # the headline (and the perf gate's knob): the highest step
            # that met the SLO. Absent when NO step did — a knee-less
            # artifact is a failed run, and the schema gate says so.
            **({} if knee is None else {"knee_qps": knee["qps"]}),
            "knee": None if knee is None else {
                "qps": knee["qps"],
                "p50_s": knee["p50_s"],
                "p99_s": knee["p99_s"],
                "shed_rate": knee["shed_rate"],
                "waterfall": knee.get("waterfall", {}),
            },
        }
        if sampler_obj is not None:
            result["sampler"] = sampler_obj.dump(top_n=20)
        return result


def run_harness(config: HarnessConfig | None = None) -> dict:
    return LoadHarness(config).run()


def write_loadtest(result: dict, path: str = "LOADTEST.json") -> str:
    """Atomic write of the LOADTEST payload (tmp+rename, the BASELINE/
    BENCH idiom) — ``tools_perf_gate.py --check-schema`` reads this."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path
