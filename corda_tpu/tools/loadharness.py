"""Open-loop SLO-attainment harness.

The closed-loop harness (``loadtest.py``) measures what N injector
threads can push: each thread waits for its command to finish before
issuing the next, so the moment the system slows down the offered load
*politely backs off* — latency quantiles flatten exactly when they
should explode, and the measured "capacity" is really "capacity at the
concurrency the harness happened to pick" (coordinated omission).

This harness is OPEN-LOOP: arrivals are a seeded Poisson process at a
fixed target rate, and the arrival clock NEVER waits for completions.
A flow's latency is measured from its *scheduled arrival time* — if the
system (or the submitting thread) falls behind, the backlog shows up in
p99 instead of silently stretching the inter-arrival gaps. Offered load
the system cannot absorb accumulates as in-flight backlog until the
``max_inflight`` bound, past which arrivals are SHED and counted (the
open-loop analogue of an admission reject — the arrival still happened).

A run is a stepped qps ramp. Each step is scored through a private
``SLOMonitor`` (PR 7's attainment machinery, breach-latch only): the
windowed p99 and the error+shed rate are checked against the configured
objective, and the KNEE is the highest step whose SLO held. Per-step
flowprof waterfalls (``configure_flowprof(reset=True)`` between steps)
say where the wall went as the knee approaches — queue wait and lock
wait grow, device execute does not. Results land in ``LOADTEST.json``
(schema checked by ``tools_perf_gate.py --check-schema``); the CLI is
``tools_loadgen.py``. Knobs and method: docs/LOAD_HARNESS.md. Metric
names (``loadharness.*``): docs/OBSERVABILITY.md §"Critical-path
accounting".

Toggles compose with the chaos/durability/resilience tiers: a
``FaultPlan`` runs the ramp under injected message loss, ``durable=True``
puts every node on WAL-backed checkpoints (fsync wait appears in the
waterfall), ``resilience=True`` serves verification through a
self-healing scheduler.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import tempfile
import threading
import time

LOADTEST_SCHEMA = 1


@dataclasses.dataclass
class HarnessConfig:
    """One ramp's knobs (docs/LOAD_HARNESS.md has the full table)."""

    qps_steps: tuple = (4.0, 8.0, 16.0)
    step_duration_s: float = 5.0
    drain_timeout_s: float = 30.0
    seed: int = 2026
    # the SLO each step is scored against
    p99_slo_s: float = 2.0
    max_error_rate: float = 0.05
    min_samples: int = 5
    # open-loop shed bound: arrivals past this in-flight depth are shed
    max_inflight: int = 256
    # workload: "payment" (issue setup + CashPaymentFlow arrivals, full
    # flow→verify→notary path) or "issue" (CashIssueFlow arrivals only,
    # no notary leg — cheaper, for pure engine saturation)
    workload: str = "payment"
    use_device: bool = False        # device-batched signature verify
    # toggles
    chaos: object | None = None     # a faultinject.FaultPlan, or None
    durable: bool = False           # WAL-backed checkpoints on every node
    resilience: bool = False        # self-healing serving policy
    flowprof: bool = True           # per-step waterfalls
    sampler: bool = False           # attach folded stacks to the result
    netstats: bool = True           # per-step edge retransmit/transit


def _quantile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


class _StepStats:
    """One step's outcome ledger (thread-safe: completions land from
    flow-worker callback threads while the arrival clock runs)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latencies: list[float] = []
        self.errors = 0
        self.shed = 0
        self.offered = 0

    def complete(self, latency_s: float, error: bool) -> None:
        with self.lock:
            if error:
                self.errors += 1
            else:
                self.latencies.append(latency_s)


class LoadHarness:
    """Builds the mocknet fixture, runs the ramp, scores the steps."""

    def __init__(self, config: HarnessConfig | None = None):
        self.config = config or HarnessConfig()
        self._rng = random.Random(self.config.seed)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._idle = threading.Condition(self._inflight_lock)

    # ------------------------------------------------------------ fixture
    def _build(self, stack):
        """Create the 3-node mocknet (+ optional durability/resilience/
        chaos tiers) on ``stack`` (an ExitStack owning teardown)."""
        from corda_tpu.testing.mocknet import MockNetworkNodes
        from corda_tpu.verifier import BatchedVerifierService

        cfg = self.config
        chaos_injector = None
        if cfg.chaos is not None:
            from corda_tpu.faultinject import FaultInjector
            from corda_tpu.faultinject import clear as clear_injector
            from corda_tpu.faultinject import install as install_injector

            chaos_injector = FaultInjector(cfg.chaos)
            install_injector(chaos_injector)
            stack.callback(clear_injector)
        if cfg.resilience:
            from corda_tpu.serving import ResiliencePolicy, configure_scheduler

            configure_scheduler(
                use_device_default=cfg.use_device,
                resilience=ResiliencePolicy(flight_dump_on_quarantine=False),
            )
        net = stack.enter_context(MockNetworkNodes())
        if chaos_injector is not None:
            # the global install() above feeds the named fault SITES
            # (check_site); transport drop/delay/partition decisions are
            # made by the NETWORK's own injector reference — without this
            # the chaos plan never touches a delivery
            net.net.set_fault_injector(chaos_injector)
            stack.callback(lambda: net.net.set_fault_injector(None))
        checkpoints = None
        if cfg.durable:
            from corda_tpu.durability import DurableStore
            from corda_tpu.flows.checkpoints import WalCheckpointStorage

            base = stack.enter_context(
                tempfile.TemporaryDirectory(prefix="loadharness-")
            )

            def checkpoints(name):
                return WalCheckpointStorage(
                    DurableStore(os.path.join(base, name), name="flows")
                )
        sender = net.create_node(
            "HarnessA",
            checkpoints=None if checkpoints is None else checkpoints("a"),
        )
        receiver = net.create_node(
            "HarnessB",
            checkpoints=None if checkpoints is None else checkpoints("b"),
        )
        notary = net.create_notary_node("HarnessNotary")
        vsvc = BatchedVerifierService(use_device=cfg.use_device)
        sender.services.transaction_verifier_service = vsvc
        stack.callback(vsvc.shutdown)
        return net, sender, receiver, notary

    # ------------------------------------------------------------- arrival
    def _start_request(self, sender, receiver, notary, stats: _StepStats,
                       scheduled_t: float) -> None:
        """Submit one arrival (non-blocking) and wire its completion back
        into ``stats``. Latency runs from the SCHEDULED arrival time."""
        from corda_tpu.finance import CashIssueFlow, CashPaymentFlow

        cfg = self.config
        if cfg.workload == "payment":
            flow = CashPaymentFlow(1, "GBP", receiver.party)
        else:
            flow = CashIssueFlow(1, "GBP", b"\x77", notary.party)
        with self._inflight_lock:
            if self._inflight >= cfg.max_inflight:
                stats.shed += 1
                return
            self._inflight += 1
        try:
            handle = sender.smm.start_flow(flow)
        except Exception:
            with self._inflight_lock:
                self._inflight -= 1
                self._idle.notify_all()
            stats.complete(0.0, error=True)
            return

        def done(fut, _t0=scheduled_t):
            latency = time.monotonic() - _t0
            err = fut.exception() is not None
            stats.complete(latency, error=err)
            with self._inflight_lock:
                self._inflight -= 1
                self._idle.notify_all()

        handle.result.add_done_callback(done)

    def _drain(self, deadline_s: float) -> bool:
        with self._inflight_lock:
            return self._idle.wait_for(
                lambda: self._inflight == 0, timeout=deadline_s
            )

    # ---------------------------------------------------------------- run
    def _run_step(self, qps: float, fixture) -> dict:
        """One open-loop step: Poisson arrivals at ``qps`` for
        ``step_duration_s``, drain, score through a private SLOMonitor."""
        from corda_tpu.node.monitoring import node_metrics
        from corda_tpu.observability.slo import SLOMonitor, SLOObjective

        net, sender, receiver, notary = fixture
        cfg = self.config
        stats = _StepStats()
        monitor = SLOMonitor(
            objectives=(SLOObjective(
                name=f"loadharness@{qps:g}qps", priority="harness",
                p99_s=cfg.p99_slo_s, max_error_rate=cfg.max_error_rate,
                window_s=cfg.step_duration_s + cfg.drain_timeout_s + 60.0,
                min_samples=cfg.min_samples,
            ),),
            breach_handler=None,  # latch only: scoring, not paging
        )
        if cfg.flowprof:
            from corda_tpu.observability.flowprof import configure_flowprof

            configure_flowprof(enabled=True, reset=True)
        if cfg.netstats:
            from corda_tpu.messaging.netstats import configure_netstats

            configure_netstats(enabled=True, reset=True)
        # when the telemetry timeline rides along (tools_loadgen.py
        # --timeline), stamp the ramp's step boundaries into the mark
        # deque so a rendered timeline names which qps each ring segment
        # was recorded under
        from corda_tpu.observability.timeseries import active_timeline

        tl = active_timeline()
        if tl is not None:
            tl.mark("loadharness.step_qps", float(qps))
        t_start = time.monotonic()
        next_arrival = t_start
        end = t_start + cfg.step_duration_s
        offered = 0
        while next_arrival < end:
            now = time.monotonic()
            if next_arrival > now:
                time.sleep(next_arrival - now)
            # the arrival HAPPENS at its scheduled instant even when the
            # clock thread woke late — open-loop latency runs from here
            self._start_request(sender, receiver, notary, stats,
                                next_arrival)
            offered += 1
            next_arrival += self._rng.expovariate(qps)
        stats.offered = offered
        drained = self._drain(cfg.drain_timeout_s)
        step_wall = time.monotonic() - t_start
        if not drained:
            # whatever is still in flight timed out the drain: score each
            # as an error with the drain-bounded latency (open-loop: they
            # were offered, so they count)
            with self._inflight_lock:
                stuck = self._inflight
            for _ in range(stuck):
                stats.complete(step_wall, error=True)
        # feed + evaluate the private SLO monitor
        with stats.lock:
            lats = sorted(stats.latencies)
            errors = stats.errors
            shed = stats.shed
        for lat in lats:
            monitor.observe("harness", lat)
        for _ in range(errors):
            monitor.observe("harness", None, error=True)
        for _ in range(shed):
            monitor.observe("harness", None, error=True)
        statuses = monitor.evaluate()
        slo_ok = bool(statuses) and not any(s["breached"] for s in statuses)
        completed = len(lats)
        denom = completed + errors + shed
        step = {
            "qps": qps,
            "offered": offered,
            "completed": completed,
            "errors": errors,
            "shed": shed,
            "shed_rate": (shed / denom) if denom else 0.0,
            "error_rate": ((errors + shed) / denom) if denom else 0.0,
            "p50_s": _quantile(lats, 0.5),
            "p99_s": _quantile(lats, 0.99),
            "drained": drained,
            "wall_s": step_wall,
            "slo_ok": slo_ok,
            "slo": statuses,
        }
        # network-path telemetry (always numeric — the schema gate
        # requires the keys even when the netstats toggle is off)
        retransmits, net_p99 = 0, 0.0
        if cfg.netstats:
            from corda_tpu.messaging.netstats import active_netstats

            nets = active_netstats()
            if nets is not None:
                retransmits = nets.total_retransmits()
                net_p99 = nets.transit_p99_s()
        step["retransmits"] = retransmits
        step["net_transit_p99_s"] = net_p99
        if cfg.flowprof:
            step["waterfall"] = self._waterfall()
        m = node_metrics()
        m.timer("loadharness.step_p99_s").update(step["p99_s"])
        m.counter("loadharness.offered").inc(offered)
        m.counter("loadharness.shed").inc(shed)
        return step

    def _waterfall(self) -> dict:
        """The step's flowprof aggregate for the workload's flow class:
        phase seconds + each phase's share of the class's total wall
        (phases sum to wall by construction — the schema gate checks)."""
        from corda_tpu.observability.flowprof import flowprof_section

        section = flowprof_section()
        classes = section.get("classes", {})
        want = ("CashPaymentFlow" if self.config.workload == "payment"
                else "CashIssueFlow")
        for cls, agg in classes.items():
            if cls.endswith(want):
                return {
                    "flow_class": cls,
                    "flows": agg["flows"],
                    "wall_s": agg["wall_s"],
                    "phases": agg["phases"],
                    "shares": agg["shares"],
                }
        return {"flow_class": want, "flows": 0, "wall_s": 0.0,
                "phases": {}, "shares": {}}

    def run(self) -> dict:
        """The full ramp. Returns the LOADTEST payload (see
        ``write_loadtest`` for the file half)."""
        import contextlib

        from corda_tpu.finance import CashIssueFlow

        cfg = self.config
        sampler_obj = None
        if cfg.sampler:
            from corda_tpu.observability.sampler import configure_sampler

            sampler_obj = configure_sampler(enabled=True, reset=True)
        try:
            with contextlib.ExitStack() as stack:
                fixture = self._build(stack)
                net, sender, receiver, notary = fixture
                # ---- setup (UNMEASURED): pre-issue one 1-GBP state per
                # expected payment so arrivals never contend on selection
                # and never run out of cash mid-step
                if cfg.workload == "payment":
                    expected = sum(
                        int(q * cfg.step_duration_s * 1.5) + 8
                        for q in cfg.qps_steps
                    )
                    for _ in range(expected):
                        sender.run_flow(
                            CashIssueFlow(1, "GBP", b"\x77", notary.party)
                        )
                steps = [self._run_step(q, fixture) for q in cfg.qps_steps]
        finally:
            if cfg.flowprof:
                from corda_tpu.observability.flowprof import (
                    configure_flowprof,
                )

                configure_flowprof(enabled=False, reset=True)
            if cfg.netstats:
                from corda_tpu.messaging.netstats import configure_netstats

                configure_netstats(enabled=False, reset=True)
            if sampler_obj is not None:
                from corda_tpu.observability.sampler import configure_sampler

                configure_sampler(enabled=False)
            if cfg.resilience:
                from corda_tpu.serving.scheduler import shutdown_scheduler

                shutdown_scheduler()
        knee = None
        for step in steps:
            if step["slo_ok"]:
                knee = step
        if knee is not None:
            from corda_tpu.observability.timeseries import active_timeline

            tl = active_timeline()
            if tl is not None:
                tl.mark("loadharness.knee_qps", float(knee["qps"]))
        result = {
            "schema": LOADTEST_SCHEMA,
            "mode": "open-loop-poisson",
            "config": {
                "qps_steps": list(cfg.qps_steps),
                "step_duration_s": cfg.step_duration_s,
                "seed": cfg.seed,
                "p99_slo_s": cfg.p99_slo_s,
                "max_error_rate": cfg.max_error_rate,
                "max_inflight": cfg.max_inflight,
                "workload": cfg.workload,
                "use_device": cfg.use_device,
                "chaos": cfg.chaos is not None,
                "durable": cfg.durable,
                "resilience": cfg.resilience,
            },
            "steps": steps,
            # the headline (and the perf gate's knob): the highest step
            # that met the SLO. Absent when NO step did — a knee-less
            # artifact is a failed run, and the schema gate says so.
            **({} if knee is None else {"knee_qps": knee["qps"]}),
            "knee": None if knee is None else {
                "qps": knee["qps"],
                "p50_s": knee["p50_s"],
                "p99_s": knee["p99_s"],
                "shed_rate": knee["shed_rate"],
                "waterfall": knee.get("waterfall", {}),
            },
        }
        if sampler_obj is not None:
            result["sampler"] = sampler_obj.dump(top_n=20)
        return result


def run_harness(config: HarnessConfig | None = None) -> dict:
    return LoadHarness(config).run()


def run_causal(base: HarnessConfig, knee_qps: float, *,
               phases=("host_verify", "serialize", "checkpoint"),
               speedups=(0.5,), probe_duration_s: float = 4.0) -> dict:
    """Virtual-speedup experiments at the knee (``tools_loadgen.py
    --causal`` — docs/OBSERVABILITY.md §Causal profiler): each cell is
    a fresh single-step harness run at the knee's arrival rate with the
    causal profiler dilating every delayable non-target phase by
    ``k−1`` of its booked duration.

    The prediction is latency-corrected rather than the synthetic run's
    pure ``k × measured`` rescale. On this workload the inserted sleeps
    ride each flow's own path (they stretch that flow's wall) but
    release the GIL, so a *saturated* probe's goodput barely moves and
    the naked rescale would report ``k×`` for every phase. Instead each
    probe runs arrival-limited at the knee and the cell recovers the
    predicted per-flow service time from flowprof's own accounting:

        L_pred = L_E − (k−1)·ô − x·p̂

    (``L_E`` mean per-flow wall under the experiment, ``ô`` the
    per-flow booked seconds of delayable non-target phases — what the
    experiment dilated — and ``p̂`` the target phase's own per-flow
    booking), then scales the knee by the service-time ratio:
    ``predicted_qps = knee_qps × L₀ / L_pred``. Returns the recorded
    ``causal`` section (``source: "loadharness"`` — no
    planted-bottleneck validation key, that is the synthetic run's
    contract).

    ``probe_duration_s`` trades runtime for ledger stability: each cell
    is one fresh probe, so run-to-run jitter in mean flow wall (notary
    RTT variance, warmup) lands directly in the predicted gain. Probes
    under ~4s on the mocknet carry tens-of-percent noise; raise the
    duration when the ledger must discriminate small phases."""
    from corda_tpu.observability.causal import (
        CAUSAL_SCHEMA,
        DELAYABLE_PHASES,
        CausalProfiler,
        build_ledger,
        record_result,
    )
    from corda_tpu.observability.flowprof import PHASES

    probe_cfg = dataclasses.replace(
        base,
        qps_steps=(float(knee_qps),),
        step_duration_s=probe_duration_s,
        # the ramp already captured sampler/timeline artifacts
        sampler=False,
    )

    def probe_step() -> dict:
        return LoadHarness(probe_cfg).run()["steps"][0]

    def per_flow(step):
        """(mean flow wall, per-flow phase seconds) from the step's
        waterfall; None when the probe completed nothing."""
        wf = step.get("waterfall") or {}
        flows = wf.get("flows") or 0
        if not flows:
            return None
        return (
            wf["wall_s"] / flows,
            {p: v / flows for p, v in wf.get("phases", {}).items()},
        )

    profiler = CausalProfiler()
    cells: list[dict] = []
    with profiler.session():
        base_step = probe_step()
        pf0 = per_flow(base_step)
        if pf0 is None:
            raise RuntimeError(
                "causal baseline probe completed no flows — cannot "
                "measure per-flow service time"
            )
        flow_wall_0, _ = pf0
        wall0 = base_step["wall_s"]
        goodput0 = (base_step["completed"] / wall0) if wall0 > 0 else 0.0
        for phase in phases:
            if phase not in PHASES:
                raise ValueError(f"unknown flowprof phase {phase!r}")
            for x in speedups:
                k = 1.0 / (1.0 - x)
                with profiler.experiment(phase, x) as exp:
                    step = probe_step()
                wall = step["wall_s"]
                cell = {
                    "phase": phase,
                    "speedup_pct": round(x * 100.0, 3),
                    "experiment_qps": (
                        (step["completed"] / wall) if wall > 0 else 0.0
                    ),
                    "inserted_delays": exp.delays,
                    "inserted_s": round(exp.inserted_s, 6),
                    "baseline_qps": float(knee_qps),
                }
                pf = per_flow(step)
                if pf is None:
                    # the dilated probe starved out: no per-flow
                    # accounting to correct against, so no prediction
                    cell["predicted_qps"] = 0.0
                    cell["predicted_gain_qps"] = -float(knee_qps)
                    cell["predicted_gain_pct"] = -100.0
                    cells.append(cell)
                    continue
                flow_wall_e, phase_s = pf
                dilated = sum(
                    v for p, v in phase_s.items()
                    if p in DELAYABLE_PHASES and p != phase
                )
                target_s = phase_s.get(phase, 0.0)
                flow_wall_pred = max(
                    1e-9,
                    flow_wall_e - (k - 1.0) * dilated - x * target_s,
                )
                predicted = float(knee_qps) * flow_wall_0 / flow_wall_pred
                cell["flow_wall_s"] = flow_wall_e
                cell["flow_wall_pred_s"] = flow_wall_pred
                cell["predicted_qps"] = predicted
                cell["predicted_gain_qps"] = predicted - float(knee_qps)
                cell["predicted_gain_pct"] = (
                    100.0 * cell["predicted_gain_qps"] / float(knee_qps)
                    if knee_qps > 0 else 0.0
                )
                cells.append(cell)
    result = {
        "schema": CAUSAL_SCHEMA,
        "baseline_qps": float(knee_qps),
        "probe_goodput_qps": goodput0,
        "probe_flow_wall_s": flow_wall_0,
        "speedups_pct": [round(x * 100.0, 3) for x in speedups],
        "cells": cells,
        "ledger": build_ledger(cells),
        "source": "loadharness",
        "knee_qps": float(knee_qps),
        "probe_duration_s": probe_duration_s,
    }
    return record_result(result)


# ======================================================================
# Overload / metastability certification (docs/OVERLOAD.md)
# ======================================================================

OVERLOAD_SCHEMA = 1


@dataclasses.dataclass
class OverloadConfig:
    """The metastability scenario's knobs: drive the node 2–5x past its
    knee under a partition/crash storm with the overload governor ON,
    and certify (a) a goodput floor DURING the storm and (b) recovery to
    a fraction of baseline within a bounded wall AFTER it — the two
    properties a metastable system fails (goodput collapses and the
    collapse outlives the trigger)."""

    base_qps: float = 8.0           # at/near the knee found by the ramp
    overload_factor: float = 3.0    # storm offered load = factor × base
    baseline_s: float = 4.0         # unmolested goodput reference window
    storm_s: float = 6.0            # overload + chaos window
    recovery_s: float = 30.0        # max wall to recover after the storm
    recovery_window_s: float = 3.0  # goodput measurement granularity
    goodput_floor: float = 0.5      # storm goodput ≥ floor × baseline
    recovery_floor: float = 0.9     # recovered when ≥ floor × baseline
    # per-flow end-to-end deadline: a few multiples of the SLO (the
    # caller's give-up point, not the p99 target) — tight enough to shed
    # genuinely dead work, loose enough that chaos retransmit backoffs
    # alone don't kill every in-flight flow
    deadline_s: float = 6.0
    # governor knobs for the run (configure_overload)
    limit: float = 32.0             # starting AIMD concurrency limit
    slo_p99_s: float = 1.5
    retry_ratio: float = 0.5
    retry_burst: float = 32.0
    seed: int = 2026
    # arrival class mix: (priority, weight) — brownout order certifies
    # BULK sheds first and INTERACTIVE last against exactly this mix
    mix: tuple = (("interactive", 0.2), ("service", 0.5), ("bulk", 0.3))
    max_inflight: int = 1024        # open-loop backstop (NOT the governor)
    # storm composition (the existing fault fabric)
    drop_p: float = 0.08
    delay_p: float = 0.10
    partition_bursts: int = 2       # full partitions of B / the notary
    partition_burst_s: float = 0.8
    workload: str = "payment"
    durable: bool = False
    use_device: bool = False


class _PhaseStats:
    """One phase's per-class outcome ledger (thread-safe, same contract
    as _StepStats)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.ok = 0
        self.errors = 0
        self.offered: dict[str, int] = {}
        self.rejected: dict[str, int] = {}
        self.latencies: list[float] = []

    def complete(self, latency_s: float, error: bool) -> None:
        with self.lock:
            if error:
                self.errors += 1
            else:
                self.ok += 1
                self.latencies.append(latency_s)


class OverloadScenario:
    """Runs baseline → storm → recovery against the 3-node mocknet and
    scores the metastability certificate (docs/OVERLOAD.md)."""

    def __init__(self, config: OverloadConfig | None = None):
        self.config = config or OverloadConfig()
        self._rng = random.Random(self.config.seed)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._idle = threading.Condition(self._inflight_lock)

    # ----------------------------------------------------------- arrivals
    def _pick_class(self) -> str:
        r = self._rng.random()
        acc = 0.0
        for cls, w in self.config.mix:
            acc += w
            if r < acc:
                return cls
        return self.config.mix[-1][0]

    def _start(self, sender, receiver, notary, stats: _PhaseStats,
               scheduled_t: float) -> None:
        from corda_tpu.finance import CashIssueFlow, CashPaymentFlow
        from corda_tpu.flows.overload import FlowAdmissionError

        cfg = self.config
        cls = self._pick_class()
        if cfg.workload == "payment":
            flow = CashPaymentFlow(1, "GBP", receiver.party)
        else:
            flow = CashIssueFlow(1, "GBP", b"\x77", notary.party)
        # the governor's brownout keys on this (BULK → SERVICE →
        # INTERACTIVE); the scenario certifies that order holds
        flow.priority = cls
        with stats.lock:
            stats.offered[cls] = stats.offered.get(cls, 0) + 1
        with self._inflight_lock:
            if self._inflight >= cfg.max_inflight:
                stats.complete(0.0, error=True)
                return
            self._inflight += 1
        try:
            handle = sender.smm.start_flow(flow, deadline_s=cfg.deadline_s)
        except FlowAdmissionError:
            # the graceful-degradation path under certification: a cheap
            # fail-fast reject, NOT an error completion — counted per
            # class so the brownout order is checkable
            with stats.lock:
                stats.rejected[cls] = stats.rejected.get(cls, 0) + 1
            with self._inflight_lock:
                self._inflight -= 1
                self._idle.notify_all()
            return
        except Exception:
            with self._inflight_lock:
                self._inflight -= 1
                self._idle.notify_all()
            stats.complete(0.0, error=True)
            return

        def done(fut, _t0=scheduled_t):
            latency = time.monotonic() - _t0
            err = fut.exception() is not None
            stats.complete(latency, error=err)
            with self._inflight_lock:
                self._inflight -= 1
                self._idle.notify_all()

        handle.result.add_done_callback(done)

    def _phase(self, fixture, qps: float, duration_s: float,
               drain_s: float) -> tuple[_PhaseStats, float]:
        """One open-loop arrival window at ``qps``; returns (stats,
        goodput qps = ok completions / the arrival window)."""
        net, sender, receiver, notary = fixture
        stats = _PhaseStats()
        t0 = time.monotonic()
        next_arrival = t0
        end = t0 + duration_s
        while next_arrival < end:
            now = time.monotonic()
            if next_arrival > now:
                time.sleep(next_arrival - now)
            self._start(sender, receiver, notary, stats, next_arrival)
            next_arrival += self._rng.expovariate(qps)
        with self._inflight_lock:
            self._idle.wait_for(
                lambda: self._inflight == 0, timeout=drain_s
            )
        return stats, stats.ok / duration_s

    # -------------------------------------------------------------- storm
    def _storm_plans(self):
        from corda_tpu.faultinject import FaultPlan, Partition

        cfg = self.config
        sever_forever = (0, 1 << 30)
        chaos = FaultPlan(
            seed=cfg.seed, drop_p=cfg.drop_p, delay_p=cfg.delay_p,
            delay_rounds=(1, 3), duplicate_p=0.02,
        )
        # a one-sided Partition severs the node from EVERYONE — the
        # network-visible shape of both a partition and a crashed node,
        # healed by swapping the plain-chaos plan back in
        bursts = [
            FaultPlan(seed=cfg.seed + 1, drop_p=cfg.drop_p, partitions=(
                Partition(*sever_forever, frozenset({"HarnessB"})),
            )),
            FaultPlan(seed=cfg.seed + 2, drop_p=cfg.drop_p, partitions=(
                Partition(*sever_forever, frozenset({"HarnessNotary"})),
            )),
        ]
        return chaos, bursts

    def _arm_plan(self, net, plan) -> None:
        """Swap the active fault plan: the module-global install feeds
        the named fault SITES (check_site), the network-instance
        reference is what actually drops/delays deliveries — both must
        point at the same injector or the storm is a fiction."""
        from corda_tpu.faultinject import FaultInjector, install

        inj = FaultInjector(plan)
        install(inj)
        net.net.set_fault_injector(inj)

    def _storm_thread(self, net, stop: threading.Event) -> threading.Thread:
        """Drives the chaos timeline for the storm window: baseline drop/
        delay chaos throughout, with full partition/crash bursts of the
        receiver and the notary spread across it. Swapping the armed plan
        is the heal mechanism (the netstats partition detector sees the
        silence and raises ``net.partition_suspect``; the heal must then
        NOT burst)."""
        cfg = self.config
        chaos, bursts = self._storm_plans()

        def run():
            self._arm_plan(net, chaos)
            n = max(0, cfg.partition_bursts)
            if n == 0:
                stop.wait(cfg.storm_s)
                return
            gap = cfg.storm_s / (n + 1)
            for i in range(n):
                if stop.wait(max(0.0, gap - cfg.partition_burst_s / 2)):
                    break
                self._arm_plan(net, bursts[i % len(bursts)])
                if stop.wait(cfg.partition_burst_s):
                    break
                self._arm_plan(net, chaos)  # heal
            stop.wait(None)  # hold plain chaos until the storm window ends

        t = threading.Thread(target=run, daemon=True, name="overload-storm")
        t.start()
        return t

    # ---------------------------------------------------------------- run
    def run(self) -> dict:
        import contextlib

        from corda_tpu.faultinject import clear as clear_injector
        from corda_tpu.finance import CashIssueFlow
        from corda_tpu.flows.overload import (
            configure_overload,
            overload_section,
        )
        from corda_tpu.messaging.netstats import (
            active_netstats,
            configure_netstats,
        )

        cfg = self.config
        harness = LoadHarness(HarnessConfig(
            seed=cfg.seed, workload=cfg.workload, durable=cfg.durable,
            use_device=cfg.use_device, chaos=None,
        ))
        try:
            with contextlib.ExitStack() as stack:
                stack.callback(clear_injector)
                fixture = harness._build(stack)
                net, sender, receiver, notary = fixture
                stack.callback(lambda: net.net.set_fault_injector(None))
                # ---- setup (UNMEASURED): pre-issue cash for every phase
                if cfg.workload == "payment":
                    expected = int(cfg.base_qps * (
                        cfg.baseline_s
                        + cfg.overload_factor * cfg.storm_s
                        + cfg.recovery_s
                    ) * 1.5) + 16
                    for _ in range(expected):
                        sender.run_flow(
                            CashIssueFlow(1, "GBP", b"\x77", notary.party)
                        )
                # governor + netstats ON for the certified run (netstats
                # feeds the partition-suspect backoff widening)
                configure_netstats(enabled=True, reset=True)
                configure_overload(
                    enabled=True, reset=True, limit=cfg.limit,
                    slo_p99_s=cfg.slo_p99_s, retry_ratio=cfg.retry_ratio,
                    retry_burst=cfg.retry_burst,
                )
                stack.callback(
                    lambda: configure_overload(enabled=False, reset=True)
                )
                stack.callback(
                    lambda: configure_netstats(enabled=False, reset=True)
                )
                # ---- phase 1: baseline goodput at base_qps, no faults
                base_stats, base_goodput = self._phase(
                    fixture, cfg.base_qps, cfg.baseline_s,
                    drain_s=cfg.deadline_s + 3.0,
                )
                # ---- phase 2: storm — offered load at factor × base
                # under drop/delay chaos + partition/crash bursts
                stop = threading.Event()
                storm = self._storm_thread(net, stop)
                storm_stats, storm_goodput = self._phase(
                    fixture, cfg.base_qps * cfg.overload_factor,
                    cfg.storm_s, drain_s=cfg.deadline_s + 3.0,
                )
                stop.set()
                storm.join(timeout=5.0)
                clear_injector()   # full heal
                net.net.set_fault_injector(None)
                # ---- phase 3: recovery — base_qps windows until goodput
                # clears the floor or the wall expires
                t_rec0 = time.monotonic()
                recovery_goodput = 0.0
                recovery_wall_s = cfg.recovery_s
                recovered = False
                rec_stats_all: list[_PhaseStats] = []
                while time.monotonic() - t_rec0 < cfg.recovery_s:
                    rstats, rgood = self._phase(
                        fixture, cfg.base_qps, cfg.recovery_window_s,
                        drain_s=cfg.deadline_s + 2.0,
                    )
                    rec_stats_all.append(rstats)
                    recovery_goodput = rgood
                    if (base_goodput > 0
                            and rgood >= cfg.recovery_floor * base_goodput):
                        recovery_wall_s = time.monotonic() - t_rec0
                        recovered = True
                        break
                ov_snap = overload_section()
                nets = active_netstats()
                retransmits = (
                    nets.total_retransmits() if nets is not None else 0
                )
        finally:
            clear_injector()
        return self._score(
            base_stats, base_goodput, storm_stats, storm_goodput,
            recovery_goodput, recovery_wall_s, recovered,
            ov_snap, retransmits,
        )

    def _score(self, base_stats, base_goodput, storm_stats, storm_goodput,
               recovery_goodput, recovery_wall_s, recovered,
               ov_snap: dict, retransmits: int) -> dict:
        cfg = self.config
        goodput_ratio = (
            storm_goodput / base_goodput if base_goodput > 0 else 0.0
        )
        recovery_ratio = (
            recovery_goodput / base_goodput if base_goodput > 0 else 0.0
        )
        # brownout order: per-class REJECT RATE must be monotone
        # BULK ≥ SERVICE ≥ INTERACTIVE (rates, not counts — the mix is
        # not uniform). Small epsilon: one stray reject in a small
        # window must not flip the verdict.
        with storm_stats.lock:
            offered = dict(storm_stats.offered)
            rejected = dict(storm_stats.rejected)
        rates = {
            cls: (rejected.get(cls, 0) / offered[cls])
            if offered.get(cls) else 0.0
            for cls, _w in cfg.mix
        }
        eps = 0.02
        brownout_order_ok = (
            rates.get("interactive", 0.0) <= rates.get("service", 0.0) + eps
            and rates.get("service", 0.0) <= rates.get("bulk", 0.0) + eps
        )
        # retry-budget reconcile: granted never exceeds earned (the
        # governor's own invariant), and wire-observed retransmits stay
        # within granted + granted headroom — every untracked responder
        # echo (Confirm/Reject re-sent under a ``~`` wire id) is caused
        # 1:1 by a budget-granted initiator retransmit
        granted = int(ov_snap.get("retry_granted", 0))
        denied = int(ov_snap.get("retry_denied", 0))
        earned = float(ov_snap.get("budget_earned", 0.0))
        retry_budget_ok = granted <= earned and retransmits <= 2 * granted + 16
        goodput_floor_ok = goodput_ratio >= cfg.goodput_floor
        recovery_ok = recovered and recovery_ratio >= cfg.recovery_floor
        section = {
            "schema": OVERLOAD_SCHEMA,
            "base_qps": cfg.base_qps,
            "overload_qps": cfg.base_qps * cfg.overload_factor,
            "deadline_s": cfg.deadline_s,
            "baseline_goodput_qps": base_goodput,
            "storm_goodput_qps": storm_goodput,
            "goodput_ratio": goodput_ratio,
            "goodput_floor": cfg.goodput_floor,
            "goodput_floor_ok": int(goodput_floor_ok),
            "recovery_goodput_qps": recovery_goodput,
            "recovery_ratio": recovery_ratio,
            "recovery_floor": cfg.recovery_floor,
            "recovery_wall_s": recovery_wall_s,
            "recovery_wall_limit_s": cfg.recovery_s,
            "recovery_ok": int(recovery_ok),
            "offered_by_class": offered,
            "rejected_by_class": rejected,
            "reject_rate_by_class": rates,
            "brownout_order_ok": int(brownout_order_ok),
            "admission_rejected": sum(rejected.values()),
            "deadline_shed": int(ov_snap.get("deadline_shed", 0)),
            "retransmits": int(retransmits),
            "retry_budget_granted": granted,
            "retry_budget_denied": denied,
            "retry_budget_earned": earned,
            "retry_budget_ok": int(retry_budget_ok),
            "config": {
                "overload_factor": cfg.overload_factor,
                "baseline_s": cfg.baseline_s,
                "storm_s": cfg.storm_s,
                "limit": cfg.limit,
                "slo_p99_s": cfg.slo_p99_s,
                "retry_ratio": cfg.retry_ratio,
                "mix": [list(m) for m in cfg.mix],
                "drop_p": cfg.drop_p,
                "partition_bursts": cfg.partition_bursts,
                "seed": cfg.seed,
                "workload": cfg.workload,
                "durable": cfg.durable,
            },
        }
        return {"overload": section}


def run_overload(config: OverloadConfig | None = None) -> dict:
    """Run the metastability certification; returns ``{"overload": ...}``
    ready to merge into a LOADTEST/bench payload (schema checked by
    ``tools_perf_gate.py --check-schema``)."""
    return OverloadScenario(config).run()


def write_loadtest(result: dict, path: str = "LOADTEST.json") -> str:
    """Atomic write of the LOADTEST payload (tmp+rename, the BASELINE/
    BENCH idiom) — ``tools_perf_gate.py --check-schema`` reads this."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path
