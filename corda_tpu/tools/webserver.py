"""REST gateway per node.

Capability parity with the reference's webserver module
(webserver/.../WebServer.kt + internal/NodeWebServer.kt: a Jetty/Jersey
HTTP server exposing node operations as REST endpoints backed by RPC).
Endpoints:

    GET  /api/status                 node identity + time
    GET  /api/peers                  network map snapshot
    GET  /api/notaries               notary identities
    GET  /api/vault?state=<Class>    unconsumed states
    GET  /api/flows                  in-progress flows
    GET  /api/flows/registered       registered flow class paths
    POST /api/flows/<ClassPath>      start a flow; JSON body = args list;
                                     ?wait=1 blocks for the result
    GET  /api/attachments/<hash>     download an attachment

Uses the standard-library HTTP server (the runtime has no web framework);
JSON rendering covers the platform types.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


def _jsonable(obj):
    from corda_tpu.crypto import SecureHash
    from corda_tpu.ledger import Party

    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, bytes):
        return obj.hex()
    if isinstance(obj, SecureHash):
        return str(obj)
    if isinstance(obj, Party):
        return {"name": str(obj.name), "key": obj.owning_key.to_string_short()}
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(x) for x in obj]
    if hasattr(obj, "__dataclass_fields__"):
        import dataclasses

        return {
            f.name: _jsonable(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    return repr(obj)


class NodeWebServer:
    """HTTP façade over a CordaRPCOps-shaped object."""

    def __init__(self, ops, host: str = "127.0.0.1", port: int = 0):
        self._ops = ops
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _reply(self, code: int, payload) -> None:
                body = json.dumps(_jsonable(payload)).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_bytes(self, data: bytes) -> None:
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    outer._get(self)
                except Exception as e:
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

            def do_POST(self):
                try:
                    outer._post(self)
                except Exception as e:
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_port
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ routing
    def _get(self, req) -> None:
        url = urlparse(req.path)
        parts = [p for p in url.path.split("/") if p]
        query = parse_qs(url.query)
        if parts[:2] == ["api", "status"]:
            info = self._ops.node_info()
            req._reply(200, {
                "identity": info.legal_identity,
                "addresses": list(info.addresses),
                "time": self._ops.current_node_time(),
            })
        elif parts[:2] == ["api", "peers"]:
            req._reply(200, [
                i.legal_identity for i in self._ops.network_map_snapshot()
            ])
        elif parts[:2] == ["api", "notaries"]:
            req._reply(200, self._ops.notary_identities())
        elif parts[:2] == ["api", "vault"]:
            from corda_tpu.node.vault import QueryCriteria

            crit = QueryCriteria()
            if "state" in query:
                crit = QueryCriteria(
                    contract_state_types=(query["state"][0],)
                )
            page = self._ops.vault_query_by(crit)
            req._reply(200, {
                "total": page.total_states_available,
                "states": [
                    {"ref": str(sr.ref), "data": sr.state.data}
                    for sr in page.states
                ],
            })
        elif parts == ["api", "flows"]:
            req._reply(200, self._ops.state_machines_snapshot())
        elif parts == ["api", "flows", "registered"]:
            req._reply(200, self._ops.registered_flows())
        elif parts[:2] == ["api", "attachments"] and len(parts) == 3:
            from corda_tpu.crypto import SecureHash

            data = self._ops.open_attachment(
                SecureHash(bytes.fromhex(parts[2]))
            )
            if data is None:
                req._reply(404, {"error": "no such attachment"})
            else:
                req._reply_bytes(data)
        else:
            req._reply(404, {"error": f"no route for {url.path}"})

    def _post(self, req) -> None:
        url = urlparse(req.path)
        parts = [p for p in url.path.split("/") if p]
        query = parse_qs(url.query)
        if parts[:2] == ["api", "flows"] and len(parts) == 3:
            length = int(req.headers.get("Content-Length", 0))
            body = req.rfile.read(length) if length else b"[]"
            args = json.loads(body or b"[]")
            flow_id = self._ops.start_flow_dynamic(parts[2], *args)
            if query.get("wait", ["0"])[0] == "1":
                result = self._ops.flow_result(flow_id, 120)
                req._reply(200, {"flow_id": flow_id,
                                 "result": _jsonable(result)})
            else:
                req._reply(202, {"flow_id": flow_id})
        else:
            req._reply(404, {"error": f"no route for {url.path}"})

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "NodeWebServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="webserver", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
