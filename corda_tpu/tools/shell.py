"""Interactive node shell.

Capability parity with the reference's CRaSH-based shell
(node/.../shell/InteractiveShell.kt:36-40): operators start flows, inspect
the vault and state machines, and run RPC ops from a console attached to
the node. Commands:

    flow start <ClassPath> [args…]   start a flow and wait for its result
    flow list                        registered flow class paths
    flow watch                       in-progress state machines
    run <op> [args…]                 invoke any RPC operation
    vault query [StateClass]         unconsumed states
    peers                            network map snapshot
    notaries                         notary identities
    time / help / quit

Arguments parse as Python literals when possible (ints, byte strings,
quoted strings), else stay strings.
"""

from __future__ import annotations

import ast
import shlex
import sys


def _parse_arg(token: str):
    try:
        return ast.literal_eval(token)
    except (ValueError, SyntaxError):
        return token


class InteractiveShell:
    """Drives a CordaRPCOps-shaped object (local or an RPC connection
    proxy)."""

    def __init__(self, ops, out=sys.stdout):
        self._ops = ops
        self._out = out

    # ------------------------------------------------------------- output
    def _p(self, *lines) -> None:
        for line in lines:
            print(line, file=self._out)

    # ------------------------------------------------------------ command
    def run_command(self, line: str) -> bool:
        """Execute one command; returns False when the shell should exit."""
        try:
            tokens = shlex.split(line)
        except ValueError as e:
            self._p(f"parse error: {e}")
            return True
        if not tokens:
            return True
        cmd, args = tokens[0], tokens[1:]
        try:
            if cmd in ("quit", "exit", "bye"):
                return False
            elif cmd == "help":
                self._p(__doc__)
            elif cmd == "time":
                self._p(self._ops.current_node_time())
            elif cmd == "peers":
                for info in self._ops.network_map_snapshot():
                    self._p(f"  {info.legal_identity.name}  {info.addresses}")
            elif cmd == "notaries":
                for party in self._ops.notary_identities():
                    self._p(f"  {party.name}")
            elif cmd == "flow":
                self._flow(args, line)
            elif cmd == "vault":
                self._vault(args)
            elif cmd == "run":
                if not args:
                    self._p("usage: run <op> [args… | key: value, …]")
                else:
                    rest = line.strip().partition(" ")[2]
                    if ":" in rest:
                        # named-argument form through the jackson-tier
                        # parser: values convert to the op's annotated
                        # types (parties by X.500 name, hashes from hex)
                        from corda_tpu.rpc.json_support import RpcJsonMapper
                        from corda_tpu.rpc.string_calls import (
                            StringToMethodCallParser,
                        )

                        parser = StringToMethodCallParser(
                            self._ops, RpcJsonMapper(self._ops)
                        )
                        self._p(parser.invoke(rest))
                    else:
                        fn = getattr(self._ops, args[0])
                        self._p(fn(*[_parse_arg(a) for a in args[1:]]))
            else:
                self._p(f"unknown command {cmd!r} — try 'help'")
        except Exception as e:
            self._p(f"error: {type(e).__name__}: {e}")
        return True

    def _flow(self, args, raw_line: str = "") -> None:
        if not args:
            self._p("usage: flow start|list|watch")
            return
        sub = args[0]
        if sub == "list":
            for name in self._ops.registered_flows():
                self._p(f"  {name}")
        elif sub == "watch":
            for fid in self._ops.state_machines_snapshot():
                self._p(f"  {fid}")
        elif sub == "start":
            if len(args) < 2:
                self._p("usage: flow start <ClassPath> [args… | k: v, …]")
                return
            # the RAW remainder keeps quotes intact — shlex tokens would
            # strip the quoting that protects commas in X.500 names
            rest = raw_line.partition(args[1])[2].strip()
            if ":" in rest:
                # named-argument form (the reference shell's yaml-style
                # start): values convert to the flow's ANNOTATED field
                # types — parties by X.500 name, hashes from hex, amounts
                # from "100 GBP" — via the jackson-tier mapper
                import typing

                from corda_tpu.flows.api import load_class
                from corda_tpu.rpc.json_support import RpcJsonMapper
                from corda_tpu.rpc.string_calls import parse_argument_string

                cls = load_class(args[1])
                try:
                    hints = typing.get_type_hints(cls)
                except Exception:
                    hints = {}
                mapper = RpcJsonMapper(self._ops)
                kwargs = {
                    k: (mapper.parse(v, hints[k]) if k in hints else v)
                    for k, v in parse_argument_string(rest).items()
                }
                flow_id = self._ops.start_flow_dynamic(args[1], **kwargs)
            else:
                flow_id = self._ops.start_flow_dynamic(
                    args[1], *[_parse_arg(a) for a in args[2:]]
                )
            self._p(f"started {flow_id}; waiting…")
            result = self._ops.flow_result(flow_id, 120)
            self._p(f"result: {result}")
        else:
            self._p(f"unknown flow subcommand {sub!r}")

    def _vault(self, args) -> None:
        from corda_tpu.node.vault import QueryCriteria

        crit = QueryCriteria()
        if args and args[0] == "query" and len(args) > 1:
            crit = QueryCriteria(contract_state_types=(args[1],))
        page = self._ops.vault_query_by(crit)
        self._p(f"{page.total_states_available} unconsumed state(s)")
        for sr in page.states:
            self._p(f"  {sr.ref}: {sr.state.data}")

    # ------------------------------------------------------------- loop
    def repl(self, in_stream=sys.stdin) -> None:
        self._p("corda_tpu shell — 'help' for commands")
        while True:
            self._out.write(">>> ")
            self._out.flush()
            line = in_stream.readline()
            if not line or not self.run_command(line.strip()):
                break
