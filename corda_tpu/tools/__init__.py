"""Tooling & ops tier (SURVEY.md §1 layer 12, §2.7, reference: tools/ +
webserver/ + node/.../shell/):

- ``loadtest`` — generate/interpret/execute/gather load harness with
  disruption injection (tools/loadtest/.../LoadTest.kt:37-69,
  Disruption.kt).
- ``shell`` — interactive node shell over RPC (node/.../shell/
  InteractiveShell.kt).
- ``webserver`` — REST gateway per node (webserver/.../NodeWebServer.kt).
"""
