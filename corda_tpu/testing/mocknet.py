"""In-process mock network of full nodes.

Capability parity with the reference's ``MockNetwork``
(testing/node-driver/.../node/MockNode.kt:78-177): N nodes with real
services (vault, storage, identity, flows, notary) wired onto an
``InMemoryMessagingNetwork`` in one process, with background pumping for
integration-style tests or manual pumping for deterministic step-through.
This is test tier 2 of the reference's ladder (SURVEY.md §4) — protocols
exercised without processes.
"""

from __future__ import annotations

from corda_tpu.crypto import generate_keypair
from corda_tpu.flows import CheckpointStorage, StateMachineManager
from corda_tpu.ledger import CordaX500Name, Party
from corda_tpu.messaging import InMemoryMessagingNetwork
from corda_tpu.node import NetworkMapCache, NodeInfo, ServiceHub
from corda_tpu.node.identity import IdentityService, KeyManagementService


def make_test_party(name: str, city: str = "London", country: str = "GB"):
    kp = generate_keypair()
    return Party(CordaX500Name(name, city, country), kp.public), kp


class MockNode:
    """One full in-process node: ServiceHub + StateMachineManager over the
    shared mock transport (reference: MockNode, MockNode.kt:177).

    Restartable for crash/recovery scenarios (docs/DURABILITY.md): pass
    ``keypair`` (the identity must survive the restart), ``endpoint``
    (the transport handle ``net.restart_node(name)`` returned) and
    ``checkpoints`` (the durable storage the previous incarnation wrote)
    to rebuild a node from durable state alone — the kill-storm soak's
    restart path."""

    def __init__(self, net: InMemoryMessagingNetwork, name: str,
                 network_map: NetworkMapCache, party_resolver,
                 notary_service_factory=None, clock=None,
                 keypair=None, endpoint=None, checkpoints=None):
        self.keypair = keypair or generate_keypair()
        self.party = Party(
            CordaX500Name(name, "London", "GB"), self.keypair.public
        )
        identity_service = IdentityService()
        kms = KeyManagementService([self.keypair], identity_service)
        self.info = NodeInfo(("mock:" + name,), (self.party,))
        notary_service = None
        if notary_service_factory is not None:
            notary_service = notary_service_factory(self.party, self.keypair)
        self.services = ServiceHub(
            my_info=self.info,
            key_management_service=kms,
            identity_service=identity_service,
            network_map_cache=network_map,
            notary_service=notary_service,
        )
        self.smm = StateMachineManager(
            endpoint if endpoint is not None
            else net.create_node(str(self.party.name)),
            checkpoints if checkpoints is not None else CheckpointStorage(),
            self.party,
            party_resolver,
            services=self.services,
        )
        # manual-pump scheduler over SchedulableState vault outputs: tests
        # inject a clock and call scheduler.pump() to fire due activities
        # deterministically (the reference's TestClock idiom — production
        # nodes run the same service threaded, node.py)
        import time as _time

        from corda_tpu.node.scheduler import (
            NodeSchedulerService,
            make_scheduled_flow_starter,
        )

        self.scheduler = NodeSchedulerService(
            make_scheduled_flow_starter(self.smm, self.party.name),
            clock=clock or _time.time,
        )
        self.services.scheduler_service = self.scheduler
        self.scheduler.observe_vault(self.services.vault_service)

    def run_flow(self, flow, timeout: float = 60):
        """Start a flow and block for its result."""
        return self.smm.start_flow(flow).result.result(timeout=timeout)

    def stop(self):
        self.smm.stop()
        self.services.shutdown()


class MockNetworkNodes:
    """A named collection of MockNodes over one InMemoryMessagingNetwork +
    shared network map (reference: MockNetwork + InMemoryMessagingNetwork,
    with background pump or manual ``pump()`` for deterministic tests)."""

    def __init__(self, pump: bool = True):
        self.net = InMemoryMessagingNetwork()
        self.nmap = NetworkMapCache()
        self.parties: dict[str, Party] = {}
        self.nodes: dict[str, MockNode] = {}
        if pump:
            self.net.start_pumping()

    def create_node(self, name: str, notary_service_factory=None,
                    validating_notary: bool | None = None,
                    clock=None, keypair=None, endpoint=None,
                    checkpoints=None) -> MockNode:
        node = MockNode(
            self.net, name, self.nmap, self.parties.get,
            notary_service_factory, clock=clock,
            keypair=keypair, endpoint=endpoint, checkpoints=checkpoints,
        )
        self.parties[str(node.party.name)] = node.party
        if endpoint is None:
            self.nmap.add_node(node.info)
        if notary_service_factory is not None and endpoint is None:
            self.nmap.add_notary(
                node.party,
                validating=True if validating_notary is None else validating_notary,
            )
        self.nodes[name] = node
        return node

    def create_notary_node(self, name: str = "Notary",
                           validating: bool = True) -> MockNode:
        """Convenience: a node running an in-memory uniqueness notary."""
        from corda_tpu.notary import InMemoryUniquenessProvider
        from corda_tpu.notary.service import (
            SimpleNotaryService,
            ValidatingNotaryService,
        )

        cls = ValidatingNotaryService if validating else SimpleNotaryService
        return self.create_node(
            name,
            notary_service_factory=lambda party, kp: cls(
                party, kp, InMemoryUniquenessProvider()
            ),
            validating_notary=validating,
        )

    def pump(self) -> bool:
        """Deliver one round of messages (deterministic manual mode)."""
        return self.net.pump()

    def run_until_quiescent(self) -> int:
        return self.net.run_until_quiescent()

    def stop(self):
        for node in self.nodes.values():
            node.stop()
        self.net.stop_pumping()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
