"""Declarative ledger DSL for contract unit tests.

Capability parity with the reference's test DSL
(testing/test-utils/.../TestDSL.kt, LedgerDSLInterpreter.kt,
TransactionDSLInterpreter.kt):

    with ledger(notary=DUMMY_NOTARY) as l:
        with l.transaction() as tx:
            tx.output(CASH_PROGRAM_ID, "alice's cash", state)
            tx.command(Issue(), issuer_key)
            tx.verifies()
        with l.transaction() as tx:
            tx.input("alice's cash")
            tx.output(CASH_PROGRAM_ID, "bob's cash", moved)
            tx.command(Move(), alice_key)
            tx.fails_with("owners must sign")

Transactions build REAL WireTransactions (ids are Merkle roots), so the
DSL exercises the same verification path production uses; labelled outputs
resolve across transactions inside the ledger block.
"""

from __future__ import annotations

import re

from corda_tpu.ledger import (
    Party,
    StateAndRef,
    StateRef,
    TimeWindow,
    TransactionBuilder,
    TransactionVerificationException,
)


class DslAssertionError(AssertionError):
    pass


class TransactionDSL:
    def __init__(self, ledger_dsl: "LedgerDSL"):
        self._ledger = ledger_dsl
        self._builder = TransactionBuilder(notary=ledger_dsl.notary)
        self._labels: list[tuple[str, int]] = []  # (label, output index)
        self._n_outputs = 0
        self._verified = False

    # ------------------------------------------------------------- builders
    def input(self, label_or_ref) -> "TransactionDSL":
        if isinstance(label_or_ref, str):
            sar = self._ledger.resolve_label(label_or_ref)
        elif isinstance(label_or_ref, StateAndRef):
            sar = label_or_ref
        else:
            raise TypeError("input() takes a label or StateAndRef")
        self._builder.add_input_state(sar)
        return self

    def output(self, contract: str, label: str | None, data,
               **kwargs) -> "TransactionDSL":
        self._builder.add_output_state(data, contract, **kwargs)
        if label is not None:
            self._labels.append((label, self._n_outputs))
        self._n_outputs += 1
        return self

    def command(self, value, *signers) -> "TransactionDSL":
        self._builder.add_command(value, *signers)
        return self

    def time_window(self, from_time=None, until_time=None) -> "TransactionDSL":
        self._builder.set_time_window(TimeWindow(from_time, until_time))
        return self

    # ------------------------------------------------------------ verdicts
    def _ledger_tx(self):
        wtx = self._builder.to_wire_transaction()
        return wtx, wtx.to_ledger_transaction(self._ledger.resolve_state)

    def verifies(self) -> "TransactionDSL":
        """Assert the transaction verifies, then commit its outputs to the
        ledger block so later transactions can consume them."""
        wtx, ltx = self._ledger_tx()
        ltx.verify()
        self._ledger.commit(wtx, self._labels)
        self._verified = True
        return self

    def fails(self) -> "TransactionDSL":
        return self.fails_with("")

    def fails_with(self, pattern: str) -> "TransactionDSL":
        wtx, ltx = self._ledger_tx()
        try:
            ltx.verify()
        except TransactionVerificationException as e:
            if pattern and not re.search(pattern, str(e)):
                raise DslAssertionError(
                    f"transaction failed, but with {e!r} instead of "
                    f"/{pattern}/"
                ) from e
            self._verified = True
            return self
        raise DslAssertionError(
            f"transaction unexpectedly verified (wanted /{pattern}/)"
        )

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None and not self._verified:
            raise DslAssertionError(
                "transaction block ended without verifies()/fails_with()"
            )
        return False


class LedgerDSL:
    """Holds committed outputs; resolves labels and StateRefs for the
    transactions declared inside the block."""

    def __init__(self, notary: Party):
        self.notary = notary
        self._outputs: dict[StateRef, object] = {}    # ref -> TransactionState
        self._by_label: dict[str, StateAndRef] = {}

    def transaction(self) -> TransactionDSL:
        return TransactionDSL(self)

    # ------------------------------------------------------------ plumbing
    def commit(self, wtx, labels) -> None:
        for i, ts in enumerate(wtx.outputs):
            self._outputs[StateRef(wtx.id, i)] = ts
        for label, idx in labels:
            if label in self._by_label:
                raise DslAssertionError(f"duplicate output label {label!r}")
            self._by_label[label] = StateAndRef(
                wtx.outputs[idx], StateRef(wtx.id, idx)
            )

    def resolve_label(self, label: str) -> StateAndRef:
        try:
            return self._by_label[label]
        except KeyError:
            raise DslAssertionError(f"unknown output label {label!r}") from None

    def resolve_state(self, ref: StateRef):
        try:
            return self._outputs[ref]
        except KeyError:
            raise DslAssertionError(f"unresolvable input {ref}") from None

    def retrieve_output(self, label: str) -> StateAndRef:
        return self.resolve_label(label)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def ledger(notary: Party) -> LedgerDSL:
    return LedgerDSL(notary)
