"""Deterministic test identities (reference: testing/test-utils/.../
TestConstants.kt — ALICE/BOB/CHARLIE/DUMMY_NOTARY with fixed entropy keys).

Keys derive from fixed entropy so test vectors and ledger fixtures are
reproducible across runs (reference: entropyToKeyPair, Crypto.kt:811-834).
"""

from __future__ import annotations

from corda_tpu.crypto import derive_keypair_from_entropy
from corda_tpu.crypto.schemes import DEFAULT_SIGNATURE_SCHEME
from corda_tpu.ledger import CordaX500Name, Party

ALICE_NAME = CordaX500Name("Alice Corp", "Madrid", "ES")
BOB_NAME = CordaX500Name("Bob Plc", "Rome", "IT")
CHARLIE_NAME = CordaX500Name("Charlie Ltd", "Athens", "GR")
DUMMY_NOTARY_NAME = CordaX500Name("Notary Service", "Zurich", "CH")


def test_keypair(seed: int):
    """Reproducible keypair from an integer seed."""
    entropy = seed.to_bytes(8, "big") * 4
    return derive_keypair_from_entropy(DEFAULT_SIGNATURE_SCHEME, entropy)


def test_party(name: CordaX500Name, seed: int):
    kp = test_keypair(seed)
    return Party(name, kp.public), kp


ALICE, ALICE_KEY = test_party(ALICE_NAME, 10)
BOB, BOB_KEY = test_party(BOB_NAME, 20)
CHARLIE, CHARLIE_KEY = test_party(CHARLIE_NAME, 30)
DUMMY_NOTARY, DUMMY_NOTARY_KEY = test_party(DUMMY_NOTARY_NAME, 40)
