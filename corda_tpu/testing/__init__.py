"""Test infrastructure tier (SURVEY.md §4, reference: testing/test-utils +
testing/node-driver): in-process mock network of full nodes, MockServices,
deterministic test identities, the declarative ledger DSL, and the
random-valid-ledger generator used for fuzz-style verifier tests."""

from .mocknet import MockNetworkNodes, MockNode, make_test_party
from .constants import ALICE_NAME, BOB_NAME, CHARLIE_NAME, DUMMY_NOTARY_NAME
from .dsl import LedgerDSL, ledger
from .generated_ledger import GeneratedLedger
from .driver import DriverDSL, NodeHandle, driver

__all__ = [
    "MockNetworkNodes", "MockNode", "make_test_party",
    "ALICE_NAME", "BOB_NAME", "CHARLIE_NAME", "DUMMY_NOTARY_NAME",
    "LedgerDSL", "ledger", "GeneratedLedger",
    "DriverDSL", "NodeHandle", "driver",
]
