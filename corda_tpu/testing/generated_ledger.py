"""Random always-valid ledger generator.

Capability parity with the reference's ``GeneratedLedger``
(verifier/src/integration-test/.../GeneratedLedger.kt:24 over the
client/mock Generator monad): produce arbitrary VALID transaction DAGs —
issuances and value-conserving moves of a fungible test asset, fully
signed — to fuzz the verification tier (batched verifier, wavefront DAG
scheduler, notary services) with realistic shapes.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import random

from corda_tpu.crypto import SecureHash, generate_keypair, sign_tx_id
from corda_tpu.ledger import (
    Amount,
    CordaX500Name,
    Party,
    SignedTransaction,
    StateAndRef,
    StateRef,
    TransactionBuilder,
    register_contract,
)
from corda_tpu.serialization import register_custom


@dataclasses.dataclass(frozen=True)
class GenAsset:
    value: int
    owner: Party

    @property
    def participants(self):
        return [self.owner]


@dataclasses.dataclass(frozen=True)
class GenCommand:
    op: str


register_custom(
    GenAsset, "testing.GenAsset",
    to_fields=lambda s: {"value": s.value, "owner": s.owner},
    from_fields=lambda d: GenAsset(d["value"], d["owner"]),
)
register_custom(
    GenCommand, "testing.GenCommand",
    to_fields=lambda c: {"op": c.op},
    from_fields=lambda d: GenCommand(d["op"]),
)

GEN_CONTRACT_ID = "testing.GenContract"


@register_contract(GEN_CONTRACT_ID)
class GenContract:
    def verify(self, tx):
        cmds = tx.commands_of_type(GenCommand)
        if not cmds:
            raise ValueError("no GenCommand")
        ins = sum(s.value for s in tx.inputs_of_type(GenAsset))
        outs = sum(s.value for s in tx.outputs_of_type(GenAsset))
        op = cmds[0].value.op
        if op == "issue":
            if tx.inputs:
                raise ValueError("issue must not consume")
        elif ins != outs:
            raise ValueError(f"value not conserved: {ins} -> {outs}")


class GeneratedLedger:
    """Seeded generator of valid transaction DAGs.

    ``generate(n)`` returns ``{tx_id: SignedTransaction}`` where every
    transaction is fully signed (owners of consumed states + notary) and
    every input resolves inside the set — directly feedable to
    ``verify_transaction_dag`` / the batched verifier, or notarisable via
    the notary services.
    """

    def __init__(self, seed: int = 0, n_parties: int = 3,
                 notary: Party | None = None, notary_keypair=None):
        self.rng = random.Random(seed)
        self.keypairs = {}
        self.parties = []
        for i in range(n_parties):
            kp = generate_keypair()
            p = Party(CordaX500Name(f"Gen Party {i}", "City", "GB"), kp.public)
            self.keypairs[p.owning_key] = kp
            self.parties.append(p)
        if notary is None:
            nkp = generate_keypair()
            notary = Party(
                CordaX500Name("Gen Notary", "City", "GB"), nkp.public
            )
            notary_keypair = nkp
        self.notary = notary
        self.notary_keypair = notary_keypair
        self.unspent: list[tuple[StateAndRef, Party]] = []
        self.transactions: dict = {}

    # ------------------------------------------------------------- steps
    def _sign(self, builder: TransactionBuilder, signer_keys,
              with_notary: bool) -> SignedTransaction:
        wtx = builder.to_wire_transaction()
        sigs = [
            sign_tx_id(self.keypairs[k].private, k, wtx.id)
            for k in signer_keys
        ]
        if with_notary and self.notary_keypair is not None:
            sigs.append(sign_tx_id(
                self.notary_keypair.private, self.notary.owning_key, wtx.id
            ))
        return SignedTransaction.create(wtx, sigs)

    def issue(self) -> SignedTransaction:
        owner = self.rng.choice(self.parties)
        value = self.rng.randint(1, 1000)
        b = TransactionBuilder(notary=self.notary)
        n_outputs = self.rng.randint(1, 3)
        split = self._split(value, n_outputs)
        for v in split:
            b.add_output_state(GenAsset(v, owner), GEN_CONTRACT_ID)
        b.add_command(GenCommand("issue"), owner.owning_key)
        stx = self._sign(b, [owner.owning_key], with_notary=False)
        self._commit(stx, owner)
        return stx

    def move(self, with_notary_sig: bool = True) -> SignedTransaction:
        if not self.unspent:
            return self.issue()
        k = min(len(self.unspent), self.rng.randint(1, 3))
        picked_idx = self.rng.sample(range(len(self.unspent)), k)
        picked = [self.unspent[i] for i in picked_idx]
        for i in sorted(picked_idx, reverse=True):
            del self.unspent[i]
        new_owner = self.rng.choice(self.parties)
        total = sum(sar.state.data.value for sar, _ in picked)
        b = TransactionBuilder(notary=self.notary)
        signer_keys = []
        for sar, owner in picked:
            b.add_input_state(sar)
            if owner.owning_key not in signer_keys:
                signer_keys.append(owner.owning_key)
        for v in self._split(total, self.rng.randint(1, 3)):
            b.add_output_state(GenAsset(v, new_owner), GEN_CONTRACT_ID)
        b.add_command(GenCommand("move"), *signer_keys)
        stx = self._sign(b, signer_keys, with_notary=with_notary_sig)
        self._commit(stx, new_owner)
        return stx

    def _split(self, total: int, n: int) -> list[int]:
        n = max(1, min(n, total))
        cuts = sorted(self.rng.sample(range(1, total), n - 1)) if n > 1 else []
        parts = []
        prev = 0
        for c in cuts + [total]:
            parts.append(c - prev)
            prev = c
        return parts

    def _commit(self, stx: SignedTransaction, owner: Party) -> None:
        self.transactions[stx.id] = stx
        for i, ts in enumerate(stx.tx.outputs):
            self.unspent.append(
                (StateAndRef(ts, StateRef(stx.id, i)), owner)
            )

    # ------------------------------------------------------------ driver
    def generate(self, n: int, issue_fraction: float = 0.3,
                 with_notary_sig: bool = True) -> dict:
        """Generate n transactions; returns {tx_id: SignedTransaction}."""
        for _ in range(n):
            if not self.unspent or self.rng.random() < issue_fraction:
                self.issue()
            else:
                self.move(with_notary_sig=with_notary_sig)
        return dict(self.transactions)

    def stream(self, n: int, issue_fraction: float = 0.3,
               with_notary_sig: bool = True, max_unspent: int = 4096):
        """Streamed driver: yields each fully-signed transaction WITHOUT
        retaining it, and caps the unspent frontier (oldest entries are
        dropped — those states simply never get spent), so memory stays
        bounded regardless of ``n``. Same seed ⇒ same stream."""
        for _ in range(n):
            if not self.unspent or self.rng.random() < issue_fraction:
                stx = self.issue()
            else:
                stx = self.move(with_notary_sig=with_notary_sig)
            self.transactions.pop(stx.id, None)
            if len(self.unspent) > max_unspent:
                del self.unspent[: len(self.unspent) - max_unspent]
            yield stx


@dataclasses.dataclass(frozen=True)
class GenCommitRequest:
    """One streamed uniqueness-commit request: ``(refs, tx_id, caller)``
    plus whether the generator deliberately made it a double-spend (so a
    scale test knows the expected verdict without tracking state)."""

    refs: tuple
    tx_id: SecureHash
    caller: str
    expect_conflict: bool


def stream_commit_requests(
    seed: int,
    n_states: int,
    *,
    spend_fraction: float = 0.6,
    double_spend_fraction: float = 0.0,
    max_frontier: int = 8192,
    caller: str = "gen-loadtest",
):
    """Seed-deterministic stream of notary commit requests building an
    ``n_states``-output ledger with NO signing, NO state blobs and a
    bounded unspent frontier — the shape a 10^7-state conflict-check
    scale run needs (uniqueness providers never verify signatures, so a
    scale sweep over them should not pay host ed25519 costs; the signed
    path is ``GeneratedLedger.stream``). Tx ids are
    ``sha256("gen:<seed>:<counter>")`` — same seed ⇒ bit-identical
    stream. ``double_spend_fraction`` re-spends an already-consumed ref
    (a fresh tx id, so the provider MUST report a conflict); such
    requests are flagged ``expect_conflict`` and consume nothing."""
    rng = random.Random(seed)
    frontier: collections.deque = collections.deque()
    spent_ring: collections.deque = collections.deque(maxlen=1024)
    produced = 0
    counter = 0
    while produced < n_states:
        counter += 1
        tx_id = SecureHash(
            hashlib.sha256(f"gen:{seed}:{counter}".encode()).digest()
        )
        if (double_spend_fraction > 0 and spent_ring
                and rng.random() < double_spend_fraction):
            ref = spent_ring[rng.randrange(len(spent_ring))]
            yield GenCommitRequest((ref,), tx_id, caller, True)
            continue
        refs: list = []
        if frontier and rng.random() < spend_fraction:
            k = min(len(frontier), rng.randint(1, 3))
            for _ in range(k):
                refs.append(frontier.popleft())
        n_out = rng.randint(1, 3)
        yield GenCommitRequest(tuple(refs), tx_id, caller, False)
        spent_ring.extend(refs)
        for i in range(n_out):
            frontier.append(StateRef(tx_id, i))
            produced += 1
        while len(frontier) > max_frontier:
            # dropped states are simply never spent — the frontier (and
            # so generator memory) stays O(max_frontier)
            frontier.popleft()
