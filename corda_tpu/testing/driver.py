"""The driver: launch REAL node processes for integration/smoke tests.

Capability parity with the reference's driver DSL
(testing/node-driver/.../driver/Driver.kt:73-992 — ``driver { startNode(…) }``
spawning JVMs via ProcessUtilities, network-map-first start strategy,
ShutdownManager teardown) and the smoke-test tier (testing/smoke-test-utils
NodeProcess.kt: black-box child processes reached only via RPC).

Nodes run ``python -m corda_tpu.node.startup`` as subprocesses sharing a
sqlite durable-broker file as the host message fabric; the first node
started also serves the network map. Tests reach nodes via RPC over the
same fabric.

    with driver() as dsl:
        notary = dsl.start_node("O=Notary,L=Zurich,C=CH", notary=True)
        alice = dsl.start_node("O=Alice,L=London,C=GB")
        conn = dsl.rpc(alice)
        conn.proxy.ping()
"""

from __future__ import annotations

import os
import secrets
import subprocess
import sys
import tempfile
import time
from contextlib import contextmanager
from pathlib import Path


class NodeHandle:
    def __init__(self, name: str, process: subprocess.Popen, log_path: Path):
        self.name = name                      # canonical X.500 string
        self.process = process
        self.log_path = log_path

    @property
    def alive(self) -> bool:
        return self.process.poll() is None

    def kill(self) -> None:
        """Hard-kill the node process (crash simulation)."""
        self.process.kill()
        self.process.wait(timeout=10)

    def terminate(self) -> None:
        self.process.terminate()
        try:
            self.process.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.process.kill()
            self.process.wait(timeout=5)


class DriverDSL:
    DEFAULT_RPC_USER = ("driverUser", "driverPass", ("ALL",))

    def __init__(self, base_dir: str, secure: bool = False):
        self.base = Path(base_dir)
        self.base.mkdir(parents=True, exist_ok=True)
        self.broker_path = str(self.base / "fabric.db")
        self.nodes: list[NodeHandle] = []
        self._rpc_endpoints: list = []
        self._network_map_name: str | None = None
        # secure mode: the ensemble rides the mutually-authenticated
        # transport — the first node embeds + serves the broker on an
        # ephemeral port (parsed from its startup banner, no bind race),
        # later nodes and RPC clients connect as certified peers
        self.secure = secure
        self.fabric_address: str | None = None

    # ------------------------------------------------------------ nodes
    def start_node(self, legal_name: str, notary: bool = False,
                   validating: bool = True, timeout_s: float = 60,
                   cordapps: tuple = ("corda_tpu.finance",),
                   extra_config: str = "",
                   raft_cluster: tuple = ()) -> NodeHandle:
        """``raft_cluster``: canonical X.500 names of ALL members of a
        Raft notary cluster this node belongs to (reference: the
        raft-notary Cordform's clusterAddresses) — each member is its own
        process, consensus rides the shared fabric."""
        from corda_tpu.ledger import CordaX500Name

        canonical = str(CordaX500Name.parse(legal_name))
        safe = canonical.replace("=", "_").replace(",", "_").replace(" ", "")
        node_dir = self.base / safe
        node_dir.mkdir(exist_ok=True)
        user, pw, perms = self.DEFAULT_RPC_USER
        conf = node_dir / "node.conf"
        v = "true" if validating else "false"
        if notary and raft_cluster:
            peers = ", ".join(f'"{p}"' for p in raft_cluster)
            notary_block = (
                f'notary {{ validating = {v}\n'
                f'  raft {{ nodeAddress = "{canonical}"\n'
                f'    clusterAddresses = [{peers}] }} }}'
            )
        elif notary:
            notary_block = f'notary {{ validating = {v} }}'
        else:
            notary_block = ""
        # network-map-first start strategy (reference:
        # NetworkMapStartStrategy): the first node serves the map; later
        # nodes register with it by address
        map_line = ""
        if self._network_map_name is not None:
            map_line = f'networkMapAddress = "{self._network_map_name}"'
        cordapp_list = ", ".join(f'"{c}"' for c in cordapps)
        conf.write_text(f"""
            myLegalName = "{legal_name}"
            baseDirectory = "{node_dir}"
            cordappPackages = [{cordapp_list}]
            {notary_block}
            {map_line}
            rpcUsers = [{{ username = "{user}", password = "{pw}",
                           permissions = ["ALL"] }}]
            {extra_config}
        """)
        log_path = node_dir / "node.log"
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = (
            str(Path(__file__).resolve().parents[2])
            + os.pathsep + env.get("PYTHONPATH", "")
        )
        args = [
            sys.executable, "-m", "corda_tpu.node.startup",
            "--config", str(conf), "--broker", self.broker_path,
            "--no-banner",
        ]
        first_node = self._network_map_name is None
        if self.secure:
            if first_node:
                args += ["--fabric-listen", "127.0.0.1:0"]
            else:
                args += ["--fabric", self.fabric_address]
        if first_node:
            args.append("--network-map")
            self._network_map_name = canonical
        with open(log_path, "wb") as log:
            process = subprocess.Popen(
                args, stdout=log, stderr=subprocess.STDOUT, env=env,
                cwd=str(node_dir),
            )
        handle = NodeHandle(canonical, process, log_path)
        self.nodes.append(handle)
        self._await_started(handle, timeout_s)
        if self.secure and first_node:
            import re

            m = re.search(
                r"Secure fabric listening on (\S+:\d+)",
                handle.log_path.read_text(errors="replace"),
            )
            if m is None:
                raise RuntimeError(
                    f"first node did not report its fabric address:\n"
                    + handle.log_path.read_text()[-2000:]
                )
            self.fabric_address = m.group(1)
        return handle

    @staticmethod
    def _await_started(handle: NodeHandle, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not handle.alive:
                raise RuntimeError(
                    f"node {handle.name} died during startup:\n"
                    + handle.log_path.read_text()[-2000:]
                )
            if "started" in handle.log_path.read_text(errors="replace"):
                return
            time.sleep(0.2)
        raise TimeoutError(f"node {handle.name} did not start in {timeout_s}s")

    # ---------------------------------------------------------- workers
    def start_verifier_worker(self, name: str = "verifier-worker",
                              use_device: bool = False) -> NodeHandle:
        """Spawn an out-of-process verifier worker competing on the
        fabric's verifier.requests queue (reference: the Verifier jar,
        Verifier.kt:66-84). In secure mode it joins as a certified peer."""
        log_path = self.base / f"{name}.log"
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = (
            str(Path(__file__).resolve().parents[2])
            + os.pathsep + env.get("PYTHONPATH", "")
        )
        args = [sys.executable, "-m", "corda_tpu.verifier.worker",
                self.broker_path, "--name", name]
        if not use_device:
            args.append("--no-device")
        if self.secure:
            args += ["--fabric", self.fabric_address]
        with open(log_path, "wb") as log:
            process = subprocess.Popen(
                args, stdout=log, stderr=subprocess.STDOUT, env=env,
                cwd=str(self.base),
            )
        handle = NodeHandle(name, process, log_path)
        self.nodes.append(handle)  # shutdown() reaps it with the nodes
        return handle

    # -------------------------------------------------------------- rpc
    def rpc(self, node: NodeHandle, username: str | None = None,
            password: str | None = None, timeout_s: float = 30.0):
        """An RPC connection to a spawned node, over the shared fabric —
        in secure mode the client is itself a certified fabric peer (the
        reference's RPC rides the same TLS Artemis transport)."""
        from corda_tpu.messaging import BrokerMessagingClient, DurableQueueBroker
        from corda_tpu.rpc import CordaRPCClient

        user, pw, _ = self.DEFAULT_RPC_USER
        client_name = f"driver-rpc-{secrets.token_hex(4)}"
        if self.secure:
            from corda_tpu.crypto import generate_keypair
            from corda_tpu.messaging import SecureFabricClient
            from corda_tpu.node.certificates import issue_identity

            ident = issue_identity(
                f"O={client_name},L=London,C=GB", generate_keypair()
            )
            broker = SecureFabricClient(
                self.fabric_address, ident.certificate,
                ident.keypair.private, ident.trust_root,
            )
            # the endpoint name must equal the CHANNEL identity — the
            # fabric stamps every publish with it, and receivers drop
            # messages whose envelope claims a different sender
            client_name = str(ident.party.name)
        else:
            broker = DurableQueueBroker(self.broker_path)
        endpoint = BrokerMessagingClient(broker, client_name)
        self._rpc_endpoints.append((endpoint, broker))
        client = CordaRPCClient(endpoint, node.name)
        return client.start(username or user, password or pw,
                            timeout_s=timeout_s)

    # ---------------------------------------------------------- teardown
    def shutdown(self) -> None:
        for endpoint, broker in self._rpc_endpoints:
            try:
                endpoint.stop()
                broker.close()
            except Exception:
                pass
        for handle in reversed(self.nodes):
            if handle.alive:
                handle.terminate()


@contextmanager
def driver(base_dir: str | None = None, secure: bool = False):
    """reference: Driver.kt driver { } entry (:313)."""
    tmp = None
    if base_dir is None:
        tmp = tempfile.mkdtemp(prefix="corda-tpu-driver-")
        base_dir = tmp
    dsl = DriverDSL(base_dir, secure=secure)
    try:
        yield dsl
    finally:
        dsl.shutdown()
