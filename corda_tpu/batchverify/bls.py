"""Host-side min-pk BLS12-381 aggregate signatures (docs/BATCH_VERIFY.md).

Pure Python-int arithmetic end to end — no jax, no OpenSSL — so the
scheme loads on the same minimal containers as the ed25519 fallback
tier. A device pairing kernel is explicitly out of scope for this PR;
the notary needs the *aggregation* property (one 96-byte signature per
quorum round instead of f+1 ed25519 attestations), not pairing
throughput, and every consensus round performs exactly ONE
aggregate-verify.

Layout (min-pk, the Ethereum/draft-irtf-cfrg-bls-signature convention):
public keys live in G1 (48-byte compressed), signatures in G2 (96-byte
compressed). Verification is the two-pairing product check

    e(-g1, sig) · e(pk, H(m)) == 1         (single)
    e(-g1, agg) · e(Σ pk_i, H(m)) == 1     (fast aggregate, same message)

Rogue-key attacks against aggregation are closed by
proof-of-possession: ``register_pop`` verifies a self-signature over the
public key under a separate domain tag and records the key in a
process-wide registry; ``fast_aggregate_verify`` refuses unregistered
keys by default.

Tower construction (standard): Fp2 = Fp[i]/(i²+1), Fp6 = Fp2[v]/(v³-ξ)
with ξ = 1+i, Fp12 = Fp6[w]/(w²-v). The pairing is an affine ate Miller
loop run entirely in Fp12 after untwisting the G2 point (M-type twist:
(x', y') → (x'/w², y'/w³)), with a single shared final exponentiation
for pairing products. The hard part of the final exponentiation is a
generic square-and-multiply by (p⁴-p²+1)/r — slower than the
cyclotomic-optimized ladder, irrelevant at one check per quorum round.

``hash_to_g2`` is domain-separated try-and-increment with cofactor
clearing by the exact BLS12 G2 cofactor polynomial — NOT the RFC 9380
simplified-SWU encoding. It is used only for this subsystem's own
attestations (both signer and verifier run this module), never for
interop with external BLS stacks; the r·H(m) == O subgroup pin lives in
the test suite.
"""

from __future__ import annotations

import functools
import hashlib
import secrets

# ------------------------------------------------------------- parameters

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
_X = -0xD201000000010000  # the (negative) BLS12 curve parameter

_G1X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
_G1Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
_G2X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
_G2Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

# the exact G2 cofactor, from the BLS12 family polynomial evaluated at x
_H2 = (
    _X**8 - 4 * _X**7 + 5 * _X**6 - 4 * _X**4 + 6 * _X**3 - 4 * _X**2 - 4 * _X + 13
) // 9

DST_MSG = b"ctpu-bls-sig-v1:"
DST_POP = b"ctpu-bls-pop-v1:"

PUBLIC_KEY_BYTES = 48
SIGNATURE_BYTES = 96

_INV2 = pow(2, P - 2, P)
_HALF = (P - 1) // 2


class BLSError(ValueError):
    """Malformed encoding or group-membership failure."""


# ------------------------------------------------------------------- Fp2
# elements are (a0, a1) for a0 + a1·i, i² = -1

_FP2_ZERO = (0, 0)
_FP2_ONE = (1, 0)


def _fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def _fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def _fp2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def _fp2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    return ((t0 - t1) % P, ((a0 + a1) * (b0 + b1) - t0 - t1) % P)


def _fp2_sqr(a):
    a0, a1 = a
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def _fp2_scale(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def _fp2_inv(a):
    """Conjugate/norm inversion: one Fp exponentiation per call."""
    a0, a1 = a
    ninv = pow(a0 * a0 + a1 * a1, P - 2, P)
    return (a0 * ninv % P, (-a1) * ninv % P)


def _fp2_pow(a, e: int):
    out = _FP2_ONE
    while e > 0:
        if e & 1:
            out = _fp2_mul(out, a)
        a = _fp2_sqr(a)
        e >>= 1
    return out


def _fp2_mul_xi(a):
    """Multiply by the Fp6 non-residue ξ = 1 + i."""
    a0, a1 = a
    return ((a0 - a1) % P, (a0 + a1) % P)


def _fp2_sqrt(a):
    """Square root in Fp2 via the complex method (p ≡ 3 mod 4), or None.
    The result is verified by squaring, so a wrong branch can never leak
    a bogus root."""
    a0, a1 = a
    if a1 == 0:
        s = pow(a0, (P + 1) // 4, P)
        if s * s % P == a0:
            return (s, 0)
        s = pow((-a0) % P, (P + 1) // 4, P)
        if s * s % P == (-a0) % P:
            return (0, s)
        return None
    n = (a0 * a0 + a1 * a1) % P
    s = pow(n, (P + 1) // 4, P)
    if s * s % P != n:
        return None
    for t in ((a0 + s) * _INV2 % P, (a0 - s) * _INV2 % P):
        x = pow(t, (P + 1) // 4, P)
        if x * x % P == t and x != 0:
            y = a1 * pow(2 * x, P - 2, P) % P
            if _fp2_sqr((x, y)) == (a0 % P, a1 % P):
                return (x, y)
    return None


# ------------------------------------------------------------------- Fp6
# elements are (c0, c1, c2) over Fp2 for c0 + c1·v + c2·v², v³ = ξ

_FP6_ZERO = (_FP2_ZERO, _FP2_ZERO, _FP2_ZERO)
_FP6_ONE = (_FP2_ONE, _FP2_ZERO, _FP2_ZERO)


def _fp6_add(a, b):
    return tuple(_fp2_add(x, y) for x, y in zip(a, b))


def _fp6_sub(a, b):
    return tuple(_fp2_sub(x, y) for x, y in zip(a, b))


def _fp6_neg(a):
    return tuple(_fp2_neg(x) for x in a)


def _fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = _fp2_mul(a0, b0)
    t1 = _fp2_mul(a1, b1)
    t2 = _fp2_mul(a2, b2)
    c0 = _fp2_add(
        t0,
        _fp2_mul_xi(
            _fp2_sub(
                _fp2_sub(_fp2_mul(_fp2_add(a1, a2), _fp2_add(b1, b2)), t1), t2
            )
        ),
    )
    c1 = _fp2_add(
        _fp2_sub(
            _fp2_sub(_fp2_mul(_fp2_add(a0, a1), _fp2_add(b0, b1)), t0), t1
        ),
        _fp2_mul_xi(t2),
    )
    c2 = _fp2_add(
        _fp2_sub(
            _fp2_sub(_fp2_mul(_fp2_add(a0, a2), _fp2_add(b0, b2)), t0), t2
        ),
        t1,
    )
    return (c0, c1, c2)


def _fp6_mul_v(a):
    """Multiply by v: (c0, c1, c2) → (ξ·c2, c0, c1)."""
    return (_fp2_mul_xi(a[2]), a[0], a[1])


def _fp6_inv(a):
    a0, a1, a2 = a
    c0 = _fp2_sub(_fp2_sqr(a0), _fp2_mul_xi(_fp2_mul(a1, a2)))
    c1 = _fp2_sub(_fp2_mul_xi(_fp2_sqr(a2)), _fp2_mul(a0, a1))
    c2 = _fp2_sub(_fp2_sqr(a1), _fp2_mul(a0, a2))
    t = _fp2_add(
        _fp2_mul(a0, c0),
        _fp2_mul_xi(_fp2_add(_fp2_mul(a2, c1), _fp2_mul(a1, c2))),
    )
    tinv = _fp2_inv(t)
    return (_fp2_mul(c0, tinv), _fp2_mul(c1, tinv), _fp2_mul(c2, tinv))


# ------------------------------------------------------------------ Fp12
# elements are (d0, d1) over Fp6 for d0 + d1·w, w² = v

_FP12_ZERO = (_FP6_ZERO, _FP6_ZERO)
FP12_ONE = (_FP6_ONE, _FP6_ZERO)


def _fp12_add(a, b):
    return (_fp6_add(a[0], b[0]), _fp6_add(a[1], b[1]))


def _fp12_sub(a, b):
    return (_fp6_sub(a[0], b[0]), _fp6_sub(a[1], b[1]))


def _fp12_neg(a):
    return (_fp6_neg(a[0]), _fp6_neg(a[1]))


def _fp12_mul(a, b):
    t0 = _fp6_mul(a[0], b[0])
    t1 = _fp6_mul(a[1], b[1])
    c1 = _fp6_sub(
        _fp6_sub(_fp6_mul(_fp6_add(a[0], a[1]), _fp6_add(b[0], b[1])), t0),
        t1,
    )
    return (_fp6_add(t0, _fp6_mul_v(t1)), c1)


def _fp12_conj(a):
    """Conjugation over Fp6 = the p⁶-power Frobenius."""
    return (a[0], _fp6_neg(a[1]))


def _fp12_inv(a):
    t = _fp6_sub(_fp6_mul(a[0], a[0]), _fp6_mul_v(_fp6_mul(a[1], a[1])))
    tinv = _fp6_inv(t)
    return (_fp6_mul(a[0], tinv), _fp6_neg(_fp6_mul(a[1], tinv)))


def _fp12_pow(a, e: int):
    out = FP12_ONE
    while e > 0:
        if e & 1:
            out = _fp12_mul(out, a)
        a = _fp12_mul(a, a)
        e >>= 1
    return out


# p²-power Frobenius: in the w-basis the coefficient of w^k picks up
# δ^k with δ = ξ^((p²-1)/6) (an Fp2 constant, computed once at import)
_DELTA = _fp2_pow(_fp2_mul_xi(_FP2_ONE), (P * P - 1) // 6)
_DELTA_POWS = [_FP2_ONE]
for _k in range(5):
    _DELTA_POWS.append(_fp2_mul(_DELTA_POWS[-1], _DELTA))


def _fp12_frob_p2(a):
    (a0, a1, a2), (b0, b1, b2) = a
    d = _DELTA_POWS
    return (
        (a0, _fp2_mul(a1, d[2]), _fp2_mul(a2, d[4])),
        (
            _fp2_mul(b0, d[1]),
            _fp2_mul(b1, d[3]),
            _fp2_mul(b2, d[5]),
        ),
    )


# ------------------------------------------------- generic Jacobian groups

class _Field:
    """Tiny field-op bundle so ONE Jacobian implementation serves both
    G1 (Fp ints) and G2 (Fp2 pairs)."""

    __slots__ = ("add", "sub", "mul", "sqr", "neg", "inv", "zero", "one")

    def __init__(self, add, sub, mul, sqr, neg, inv, zero, one):
        self.add, self.sub, self.mul, self.sqr = add, sub, mul, sqr
        self.neg, self.inv, self.zero, self.one = neg, inv, zero, one


_F1 = _Field(
    add=lambda a, b: (a + b) % P,
    sub=lambda a, b: (a - b) % P,
    mul=lambda a, b: a * b % P,
    sqr=lambda a: a * a % P,
    neg=lambda a: (-a) % P,
    inv=lambda a: pow(a, P - 2, P),
    zero=0,
    one=1,
)
_F2 = _Field(
    add=_fp2_add,
    sub=_fp2_sub,
    mul=_fp2_mul,
    sqr=_fp2_sqr,
    neg=_fp2_neg,
    inv=_fp2_inv,
    zero=_FP2_ZERO,
    one=_FP2_ONE,
)

# curve constants b (G1: y² = x³ + 4) and b' = 4ξ (G2, M-type twist)
_B1 = 4
_B2 = (4, 4)


def _jac_is_inf(pt, f):
    return pt[2] == f.zero


def _jac_dbl(pt, f):
    if _jac_is_inf(pt, f):
        return pt
    x, y, z = pt
    a = f.sqr(x)
    b = f.sqr(y)
    c = f.sqr(b)
    d = f.sub(f.sub(f.sqr(f.add(x, b)), a), c)
    d = f.add(d, d)
    e = f.add(f.add(a, a), a)
    g = f.sqr(e)
    x3 = f.sub(g, f.add(d, d))
    c8 = f.add(c, c)
    c8 = f.add(c8, c8)
    c8 = f.add(c8, c8)
    y3 = f.sub(f.mul(e, f.sub(d, x3)), c8)
    z3 = f.mul(f.add(y, y), z)
    return (x3, y3, z3)


def _jac_add(p1, p2, f):
    if _jac_is_inf(p1, f):
        return p2
    if _jac_is_inf(p2, f):
        return p1
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    z1z1 = f.sqr(z1)
    z2z2 = f.sqr(z2)
    u1 = f.mul(x1, z2z2)
    u2 = f.mul(x2, z1z1)
    s1 = f.mul(f.mul(y1, z2), z2z2)
    s2 = f.mul(f.mul(y2, z1), z1z1)
    if u1 == u2:
        if s1 == s2:
            return _jac_dbl(p1, f)
        return (f.one, f.one, f.zero)
    h = f.sub(u2, u1)
    i = f.sqr(f.add(h, h))
    j = f.mul(h, i)
    rr = f.sub(s2, s1)
    rr = f.add(rr, rr)
    v = f.mul(u1, i)
    x3 = f.sub(f.sub(f.sqr(rr), j), f.add(v, v))
    s1j = f.mul(s1, j)
    y3 = f.sub(f.mul(rr, f.sub(v, x3)), f.add(s1j, s1j))
    z3 = f.mul(f.sub(f.sub(f.sqr(f.add(z1, z2)), z1z1), z2z2), h)
    return (x3, y3, z3)


def _jac_neg(pt, f):
    return (pt[0], f.neg(pt[1]), pt[2])


def _jac_mul(pt, k: int, f):
    if k < 0:
        return _jac_mul(_jac_neg(pt, f), -k, f)
    out = (f.one, f.one, f.zero)
    for i in range(k.bit_length() - 1, -1, -1):
        out = _jac_dbl(out, f)
        if (k >> i) & 1:
            out = _jac_add(out, pt, f)
    return out


def _jac_to_affine(pt, f):
    """→ (x, y) or None for infinity."""
    if _jac_is_inf(pt, f):
        return None
    zi = f.inv(pt[2])
    zi2 = f.sqr(zi)
    return (f.mul(pt[0], zi2), f.mul(f.mul(pt[1], zi2), zi))


def _on_curve(aff, f, b) -> bool:
    if aff is None:
        return True
    x, y = aff
    return f.sqr(y) == f.add(f.mul(f.sqr(x), x), b)


_G1_GEN = (_G1X, _G1Y, 1)
_G2_GEN = (_G2X, _G2Y, _FP2_ONE)
assert _on_curve((_G1X, _G1Y), _F1, _B1)
assert _on_curve((_G2X, _G2Y), _F2, _B2)


# ----------------------------------------------------------------- pairing

def _fp12_from_fp(a: int):
    return (((a % P, 0), _FP2_ZERO, _FP2_ZERO), _FP6_ZERO)


def _fp12_from_fp2(a):
    return ((a, _FP2_ZERO, _FP2_ZERO), _FP6_ZERO)


# w as an Fp12 element, and the untwist factors 1/w², 1/w³
_W = (_FP6_ZERO, _FP6_ONE)
_W2_INV = _fp12_inv(_fp12_mul(_W, _W))
_W3_INV = _fp12_inv(_fp12_mul(_fp12_mul(_W, _W), _W))


def _untwist(aff2):
    """E'(Fp2) → E(Fp12) for the M-type twist: (x', y') → (x'/w², y'/w³)."""
    if aff2 is None:
        return None
    x, y = aff2
    return (
        _fp12_mul(_fp12_from_fp2(x), _W2_INV),
        _fp12_mul(_fp12_from_fp2(y), _W3_INV),
    )


def _line_dbl(r, p_at):
    """Tangent line at R evaluated at P, plus 2R (affine Fp12)."""
    xr, yr = r
    xp, yp = p_at
    xr2 = _fp12_mul(xr, xr)
    m = _fp12_mul(
        _fp12_add(_fp12_add(xr2, xr2), xr2), _fp12_inv(_fp12_add(yr, yr))
    )
    line = _fp12_sub(_fp12_mul(m, _fp12_sub(xp, xr)), _fp12_sub(yp, yr))
    x2 = _fp12_sub(_fp12_mul(m, m), _fp12_add(xr, xr))
    y2 = _fp12_sub(_fp12_mul(m, _fp12_sub(xr, x2)), yr)
    return line, (x2, y2)


def _line_add(r, q, p_at):
    """Chord through R and Q evaluated at P, plus R+Q (affine Fp12).
    The Miller loop below never meets R = ±Q mid-loop (the loop count is
    far below the group order), so the vertical-line case cannot occur."""
    xr, yr = r
    xq, yq = q
    xp, yp = p_at
    m = _fp12_mul(_fp12_sub(yq, yr), _fp12_inv(_fp12_sub(xq, xr)))
    line = _fp12_sub(_fp12_mul(m, _fp12_sub(xp, xr)), _fp12_sub(yp, yr))
    x3 = _fp12_sub(_fp12_sub(_fp12_mul(m, m), xr), xq)
    y3 = _fp12_sub(_fp12_mul(m, _fp12_sub(xr, x3)), yr)
    return line, (x3, y3)


def _miller_loop(q12, p12):
    """Affine ate Miller loop over |x|; the caller conjugates for x < 0."""
    if q12 is None or p12 is None:
        return FP12_ONE
    t = abs(_X)
    f = FP12_ONE
    r = q12
    for i in range(t.bit_length() - 2, -1, -1):
        line, r = _line_dbl(r, p12)
        f = _fp12_mul(_fp12_mul(f, f), line)
        if (t >> i) & 1:
            line, r = _line_add(r, q12, p12)
            f = _fp12_mul(f, line)
    return _fp12_conj(f)  # x < 0


_HARD_EXP = (P**4 - P**2 + 1) // R


def _final_exponentiate(f):
    """(p¹²-1)/r = (p⁶-1)·(p²+1)·((p⁴-p²+1)/r): cheap Frobenius easy
    part, generic square-and-multiply hard part."""
    f = _fp12_mul(_fp12_conj(f), _fp12_inv(f))
    f = _fp12_mul(_fp12_frob_p2(f), f)
    return _fp12_pow(f, _HARD_EXP)


def _pairing_product_is_one(pairs) -> bool:
    """Π e(P_i, Q_i) == 1 with one shared final exponentiation.
    ``pairs`` holds (G1 jacobian, G2 jacobian); identity members
    contribute a factor of 1 and are skipped."""
    f = FP12_ONE
    for g1pt, g2pt in pairs:
        a1 = _jac_to_affine(g1pt, _F1)
        a2 = _jac_to_affine(g2pt, _F2)
        if a1 is None or a2 is None:
            continue
        p12 = (_fp12_from_fp(a1[0]), _fp12_from_fp(a1[1]))
        f = _fp12_mul(f, _miller_loop(_untwist(a2), p12))
    return _final_exponentiate(f) == FP12_ONE


# ------------------------------------------------------------ serialization
# ZCash-style compressed flags: 0x80 = compressed (always set),
# 0x40 = infinity, 0x20 = y lexicographically "large"

def _fp2_sgn(y) -> int:
    y0, y1 = y
    if y1 != 0:
        return 1 if y1 > _HALF else 0
    return 1 if y0 > _HALF else 0


def g1_compress(pt) -> bytes:
    aff = _jac_to_affine(pt, _F1)
    if aff is None:
        return bytes([0xC0]) + bytes(47)
    x, y = aff
    flags = 0x80 | (0x20 if y > _HALF else 0)
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g1_decompress(blob: bytes):
    if len(blob) != PUBLIC_KEY_BYTES:
        raise BLSError("G1 point must be 48 bytes")
    flags = blob[0] & 0xE0
    if not flags & 0x80:
        raise BLSError("uncompressed G1 encoding not supported")
    if flags & 0x40:
        if any(blob[1:]) or blob[0] != 0xC0:
            raise BLSError("malformed G1 infinity encoding")
        return (1, 1, 0)
    x = int.from_bytes(bytes([blob[0] & 0x1F]) + blob[1:], "big")
    if x >= P:
        raise BLSError("G1 x coordinate out of range")
    y2 = (x * x % P * x + _B1) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise BLSError("G1 x is not on the curve")
    if (y > _HALF) != bool(flags & 0x20):
        y = P - y
    return (x, y, 1)


def g2_compress(pt) -> bytes:
    aff = _jac_to_affine(pt, _F2)
    if aff is None:
        return bytes([0xC0]) + bytes(95)
    (x0, x1), y = aff
    flags = 0x80 | (0x20 if _fp2_sgn(y) else 0)
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g2_decompress(blob: bytes):
    if len(blob) != SIGNATURE_BYTES:
        raise BLSError("G2 point must be 96 bytes")
    flags = blob[0] & 0xE0
    if not flags & 0x80:
        raise BLSError("uncompressed G2 encoding not supported")
    if flags & 0x40:
        if any(blob[1:]) or blob[0] != 0xC0:
            raise BLSError("malformed G2 infinity encoding")
        return (_FP2_ONE, _FP2_ONE, _FP2_ZERO)
    x1 = int.from_bytes(bytes([blob[0] & 0x1F]) + blob[1:48], "big")
    x0 = int.from_bytes(blob[48:], "big")
    if x0 >= P or x1 >= P:
        raise BLSError("G2 x coordinate out of range")
    x = (x0, x1)
    y2 = _fp2_add(_fp2_mul(_fp2_sqr(x), x), _B2)
    y = _fp2_sqrt(y2)
    if y is None:
        raise BLSError("G2 x is not on the curve")
    if _fp2_sgn(y) != (1 if flags & 0x20 else 0):
        y = _fp2_neg(y)
    return (x, y, _FP2_ONE)


# --------------------------------------------------------------- hash to G2

@functools.lru_cache(maxsize=512)
def hash_to_g2(msg: bytes, dst: bytes = DST_MSG):
    """Domain-separated try-and-increment onto G2, cofactor-cleared by
    the exact BLS12 cofactor polynomial so the output lands in the
    r-order subgroup (test-pinned: r·H(m) == O). Cached: one quorum
    round hashes the same outcome bytes on every replica AND the
    aggregating client."""
    ctr = 0
    while True:
        seed = dst + len(msg).to_bytes(8, "big") + msg + ctr.to_bytes(4, "big")
        h0 = int.from_bytes(hashlib.sha512(seed + b"\x00").digest(), "big") % P
        h1 = int.from_bytes(hashlib.sha512(seed + b"\x01").digest(), "big") % P
        x = (h0, h1)
        y = _fp2_sqrt(_fp2_add(_fp2_mul(_fp2_sqr(x), x), _B2))
        if y is not None:
            pt = _jac_mul((x, y, _FP2_ONE), _H2, _F2)
            if not _jac_is_inf(pt, _F2):
                return pt
        ctr += 1


# ---------------------------------------------------------------- key mgmt

def derive_keypair_from_entropy(entropy: bytes) -> tuple[bytes, bytes]:
    """→ (public 48B, private 32B). Deterministic: the BFT test clusters
    derive per-replica keys from replica names so the proof-of-possession
    registry memoizes across in-process clusters."""
    sk = int.from_bytes(
        hashlib.sha512(b"ctpu.bls.sk" + entropy).digest(), "big"
    ) % R
    if sk == 0:
        sk = 1
    pk = g1_compress(_jac_mul(_G1_GEN, sk, _F1))
    return pk, sk.to_bytes(32, "big")


def generate_keypair() -> tuple[bytes, bytes]:
    return derive_keypair_from_entropy(secrets.token_bytes(32))


def _sk_int(private: bytes) -> int:
    if len(private) != 32:
        raise BLSError("BLS private key must be 32 bytes")
    sk = int.from_bytes(private, "big")
    if not 0 < sk < R:
        raise BLSError("BLS private scalar out of range")
    return sk


def public_key(private: bytes) -> bytes:
    return g1_compress(_jac_mul(_G1_GEN, _sk_int(private), _F1))


def public_key_on_curve(public: bytes) -> bool:
    """Decompression doubles as the on-curve check; the r-order subgroup
    membership is additionally enforced (cheap relative to a pairing,
    and it makes every accepted key a valid aggregation summand)."""
    try:
        pt = g1_decompress(public)
    except BLSError:
        return False
    if _jac_is_inf(pt, _F1):
        return False
    return _jac_is_inf(_jac_mul(pt, R, _F1), _F1)


# ------------------------------------------------------------------ signing

def sign(private: bytes, message: bytes, dst: bytes = DST_MSG) -> bytes:
    return g2_compress(_jac_mul(hash_to_g2(message, dst), _sk_int(private), _F2))


def verify(public: bytes, message: bytes, signature: bytes,
           dst: bytes = DST_MSG) -> bool:
    """Single-signature check e(-g1, sig)·e(pk, H(m)) == 1."""
    try:
        pk = g1_decompress(public)
        sig = g2_decompress(signature)
    except BLSError:
        return False
    if _jac_is_inf(pk, _F1) or _jac_is_inf(sig, _F2):
        return False
    return _pairing_product_is_one(
        [(_jac_neg(_G1_GEN, _F1), sig), (pk, hash_to_g2(message, dst))]
    )


def aggregate(signatures) -> bytes:
    """Sum of G2 signature points → one 96-byte aggregate."""
    if not signatures:
        raise BLSError("cannot aggregate zero signatures")
    acc = (_FP2_ONE, _FP2_ONE, _FP2_ZERO)
    for sig in signatures:
        acc = _jac_add(acc, g2_decompress(sig), _F2)
    return g2_compress(acc)


def fast_aggregate_verify(publics, message: bytes, signature: bytes, *,
                          require_pop: bool = True) -> bool:
    """Same-message aggregate check e(-g1, agg)·e(Σ pk_i, H(m)) == 1.
    With ``require_pop`` (the default) every key must have passed
    proof-of-possession registration — the defense that makes the
    Σ pk_i shortcut safe against rogue-key aggregation."""
    if not publics:
        return False
    if require_pop and any(pk not in _POP_REGISTRY for pk in publics):
        return False
    try:
        sig = g2_decompress(signature)
        apk = (1, 1, 0)
        for pk in publics:
            apk = _jac_add(apk, g1_decompress(pk), _F1)
    except BLSError:
        return False
    if _jac_is_inf(apk, _F1) or _jac_is_inf(sig, _F2):
        return False
    return _pairing_product_is_one(
        [(_jac_neg(_G1_GEN, _F1), sig), (apk, hash_to_g2(message, DST_MSG))]
    )


# ------------------------------------------------------- proof of possession

_POP_REGISTRY: set = set()


def prove_possession(private: bytes) -> bytes:
    """Self-signature over the public key under the PoP domain tag."""
    return sign(private, public_key(private), dst=DST_POP)


def verify_possession(public: bytes, pop: bytes) -> bool:
    return verify(public, public, pop, dst=DST_POP)


def register_pop(public: bytes, pop: bytes) -> bool:
    """Verify a proof of possession and admit the key to the
    process-wide registry consulted by ``fast_aggregate_verify``.
    Idempotent; a registered key skips the (pairing-priced) re-check."""
    if public in _POP_REGISTRY:
        return True
    if not public_key_on_curve(public) or not verify_possession(public, pop):
        return False
    _POP_REGISTRY.add(public)
    return True


def is_registered(public: bytes) -> bool:
    return public in _POP_REGISTRY
